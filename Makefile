# One-command duality matrix — the analog of the reference's Makefile
# (reference Makefile:3-22 encodes "build + test under BOTH cfgs"; here
# the duality is sim vs std, plus the native components and the
# determinism re-check).
#
#   make check   — the default gate: native + test + determinism +
#                  bench-smoke (test tier excludes -m slow)
#   make check-full — same but with the slow tier included
#   make native  — build the C++ components (oracle + 3 transports)
#   make test    — default suite on the 8-device virtual CPU platform
#                  (sim tests, dual-mode/std tests, oracle bit-identical
#                  compare, sharded-equality tests, transports; the
#                  compile-heaviest redundant cross-check variants are
#                  marked `slow` and excluded here)
#   make test-full — the whole suite including the slow tier
#   make determinism — re-run the runtime suite with the replay checker
#                  forced on (MADSIM_TEST_CHECK_DETERMINISM=1)
#   make bench-smoke — one tiny engine measurement + the RPC bench's
#                  transport head-to-head (exercises sim AND std paths)

PY      ?= python
TESTENV ?= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
SHELL   := /bin/bash
# bash, not sh: the tier1 recipe uses `set -o pipefail`/PIPESTATUS

.PHONY: check check-full native test test-full tier1 determinism \
        bench-smoke bench-tpu-snapshot nemesis-soak explore obs-soak \
        store-soak latency-soak lint lint-soak absint-soak profile clean \
        campaign-bench flight pool-bench pool-bench-smoke \
        verify-bench verify-bench-smoke farm farm-smoke \
        services-models services-models-smoke causal causal-smoke \
        retry-soak retry-soak-smoke

check: native lint test determinism bench-smoke flight pool-bench-smoke \
       verify-bench-smoke farm-smoke services-models-smoke causal-smoke \
       retry-soak-smoke
	@echo "== make check: all gates passed =="

check-full: native lint test-full determinism bench-smoke flight \
            pool-bench-smoke verify-bench-smoke farm-smoke \
            services-models-smoke causal-smoke retry-soak-smoke
	@echo "== make check-full: all gates passed =="

# Static determinism analysis (madsim_tpu.lint): the repo-wide
# nondeterminism-leak linter (fails on any new finding; intentional
# real-mode sites carry checked `# lint: allow(rule)` pragmas) plus a
# jaxpr non-interference smoke (raft/record + raftlog/durable, all obs
# taps on) plus the interval-prover smoke (`--absint`: overflow + lane
# disjointness on raft/record across the lowering sweep, absint pragma
# staleness checked). `--format json` gives the machine-readable form
# for CI gating. The full model x config matrices are `make lint-soak`
# and `make absint-soak`.
lint:
	JAX_PLATFORMS=cpu $(PY) -m madsim_tpu.lint --jaxpr --absint

lint-soak:
	$(PY) tools/lint_soak.py

# Interval-prover soak (madsim_tpu/lint/absint.py): the full overflow
# + threefry-lane matrix (models x axes x LAYOUT_AXES, step AND run
# entries), both planted mutants (time32 sentinel decay, lane
# collision) caught with cited chains, pragma hygiene, lane census.
# The ABSINT_r10.txt evidence artifact.
absint-soak:
	JAX_PLATFORMS=cpu $(PY) tools/absint_soak.py > ABSINT_r10.txt; rc=$$?; \
	    cat ABSINT_r10.txt; exit $$rc

# Per-config step profile (tools/profile_step.py): phase wall
# breakdown by ablation differencing + XLA's HLO cost analysis, one
# JSONL row per bench config, PLUS the ISSUE-13 pool-size sweep axis
# (512/2048/8192, army on/off, flat vs readiness-indexed) attributing
# pop-argmin vs placement vs handler wall — the attribution evidence
# behind any perf claim. Pure measurement, never part of tier-1.
# PROFILE_OUT / PROFILE_CONFIGS override the artifact name and the
# arguments (config names and/or --pool-sweep; the default regenerates
# the round-9 pool-sweep artifact — pass "raftlog kvchaos raft" for
# the per-config phase rows).
PROFILE_OUT     ?= PROFILE_CPU_r07.jsonl
PROFILE_CONFIGS ?= --pool-sweep
profile:
	$(PY) tools/profile_step.py $(PROFILE_CONFIGS) > $(PROFILE_OUT)
	@cat $(PROFILE_OUT)

# Readiness-partitioned pool A/B (tools/pool_bench.py, ISSUE 13):
# same-box interleaved flat-vs-indexed bench on the army configs at
# pool_size >= 2048 — bit-identical final states asserted (traces,
# histories, latency sketches; identity over the full state implies
# identical violations for any invariant) and the >= 2x throughput
# acceptance floor enforced. The BENCH_AB_r07.txt evidence artifact.
# The smoke (one config, small batch, identity + measured speedup, no
# floor) rides `make check`.
pool-bench:
	$(PY) tools/pool_bench.py > BENCH_AB_r07.txt; rc=$$?; \
	    cat BENCH_AB_r07.txt; exit $$rc

pool-bench-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/pool_bench.py --smoke

# Device-resident verification A/B (tools/verify_bench.py, ISSUE 14):
# device == numpy verdict identity (lockstep + prefix-compacting
# runner), the host-vs-device history-hunt campaign A/B (device >= 3x
# generations/s at 65k seeds/generation with history screens on,
# bit-identical outcomes, _GEN_CACHE retraces == 1), the >= 10x
# host-transfer-bytes reduction (verdict words + flagged-seed
# histories vs full columns), and the find -> host-replay ->
# Wing-Gong-escalation path on the kvchaos lost-write mutant. The
# VERIFY_r09.txt evidence artifact; the smoke (identity + accounting +
# tiny A/B, no floors) rides `make check`.
VERIFY_BATCH  ?= 65536
VERIFY_GENS   ?= 4
VERIFY_ROUNDS ?= 2
verify-bench:
	$(PY) tools/verify_bench.py $(VERIFY_BATCH) $(VERIFY_GENS) \
	    $(VERIFY_ROUNDS) > VERIFY_r09.txt; rc=$$?; \
	    cat VERIFY_r09.txt; exit $$rc

verify-bench-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/verify_bench.py --smoke

# Fuzzing-farm soak (madsim_tpu/farm/, ISSUE 16): the pipelined-vs-
# blocking device-driver A/B (organic + loaded telemetry-drain
# regimes, bit-identical corpus/coverage/violations and byte-equal
# checkpoints, host_syncs 1/gen; floors 1.25x — organic gated on
# multi-core boxes, loaded everywhere), the 3-tenant scheduled session
# (standalone-equal splices, profiler-certified retraces == 1, tagged
# telemetry), adaptive-energy >= uniform at equal budget on the
# kvchaos mutant (aggregated over 3 roots at the needle shape), and
# the energy-off bit-identity row. The FARM_r11.txt evidence artifact;
# the smoke (tiny sizes, identity certs only, no floors) rides
# `make check`.
farm:
	$(PY) tools/farm_soak.py > FARM_r11.txt; rc=$$?; \
	    cat FARM_r11.txt; exit $$rc

farm-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/farm_soak.py --smoke

# Service-scale model soak (models/leasekv.py + models/shardkv.py,
# ISSUE 18): clean-model negatives through the new lease_safety /
# shard_coverage detectors (numpy == device bit-identical), the
# grant-after-expiry and release-before-ack mutants found by guided
# device-resident history hunts (host campaign bit-identical), each
# find ddmin-shrunk and replayed to the same seed + trace. The
# SERVICES_MODELS_r12.txt evidence artifact; the smoke (small batches,
# fewer generations) rides `make check`.
services-models:
	$(PY) tools/services_model_soak.py > SERVICES_MODELS_r12.txt; rc=$$?; \
	    cat SERVICES_MODELS_r12.txt; exit $$rc

services-models-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/services_model_soak.py --smoke

# Causal-provenance soak (obs/causal.py + the engine causal=True axis,
# ISSUE 19): the causal-off bit-identity across layouts + compaction at
# soak scale, device-folded Lamport clocks == host DAG rederivation +
# the fleet depth/width reduction, cone-vs-ring forensics on a real
# raftlog election-safety find (conflicting-COMMIT-anchored backward
# cone <= 25% of the ring, explain(causal=True) narrating the same
# violation), and the exact-vs-heuristic Perfetto arrow diff under a
# Duplicate + GrayFailure plan. The CAUSAL_r13.txt evidence artifact;
# the smoke (tiny sizes, no cone floor) rides `make check`.
causal:
	$(PY) tools/causal_soak.py > CAUSAL_r13.txt; rc=$$?; \
	    cat CAUSAL_r13.txt; exit $$rc

causal-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/causal_soak.py --smoke

# Client-retry soak (chaos.RetryPolicy + the engine retry= axis, ISSUE
# 20): clean kvchaos/shardkv armies under an aggressive policy + gray
# failure bank thousands of re-sent attempts with zero violations, the
# slow link amplifies delivered re-sends >= 2x over the quiet baseline,
# and the shardkv bug="noidem" mutant (non-idempotent retried apply) is
# found by the exactly_once-guided hunt, missed by the final-state
# shard_coverage checker on the same seeds, ddmin-shrunk under the
# campaign's RetrySpec and replayed bit-identically. The RETRY_r14.txt
# evidence artifact; the smoke (tiny sizes) rides `make check`.
retry-soak:
	$(PY) tools/retry_soak.py > RETRY_r14.txt; rc=$$?; \
	    cat RETRY_r14.txt; exit $$rc

retry-soak-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/retry_soak.py --smoke

native:
	$(MAKE) -C native

test: native
	$(TESTENV) $(PY) -m pytest tests/ -q -m "not slow"

test-full: native
	$(TESTENV) $(PY) -m pytest tests/ -q

# The driver's tier-1 gate, verbatim from ROADMAP.md — builders and CI
# run THIS, not a hand-copied variant (no native dep: pure-python tier)
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	    -m 'not slow' --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
	    | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$$' \
	    /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

determinism: native
	MADSIM_TEST_CHECK_DETERMINISM=1 $(TESTENV) \
	    $(PY) -m pytest tests/test_runtime.py tests/test_net.py \
	    tests/test_aio_interpose.py tests/test_aio_streams.py \
	    tests/test_raft_example.py -q

bench-smoke: native
	BENCH_CHILD=pingpong BENCH_PLATFORM=cpu BENCH_SEEDS=4 BENCH_STEPS=100 \
	    $(PY) bench.py
	$(PY) examples/rpc_bench.py

# Plan-randomized nemesis soak (madsim_tpu.chaos): chaos amplification
# on the kvchaos lost-write mutant, clean-model negative, ddmin shrink
# + exact replay, raftlog under a crash-storm/gray-failure plan.
# NEMESIS_SEEDS=8192 is the evidence-artifact scale; the default here
# is a quicker sanity size.
NEMESIS_SEEDS ?= 2048
nemesis-soak:
	$(PY) tools/nemesis_soak.py $(NEMESIS_SEEDS)

# Coverage-guided exploration soak (madsim_tpu.explore): guided-vs-
# uniform at equal budget on the kvchaos mutant (coverage + >=2x
# violations), campaign determinism + replay + shrink, and the
# targeted diskless-raftlog hunt. 2048 is the evidence-artifact scale
# (the hunt's generation 0 lands the committed-write-loss repro there).
EXPLORE_BUDGET ?= 2048
explore:
	$(PY) tools/explore_soak.py $(EXPLORE_BUDGET)

# Campaign driver A/B (madsim_tpu/explore/device.py): the same guided
# campaign run alternately by the host-driven and the device-resident
# driver, interleaved rounds — bit-identical outcomes, device >=3x
# generations/s at CAMPAIGN_BATCH seeds/generation, exactly one
# summary-sized host sync per generation (asserted from telemetry),
# plus the lean guided-vs-uniform quality guard. The CAMPAIGN artifact.
CAMPAIGN_BATCH  ?= 65536
CAMPAIGN_GENS   ?= 5
CAMPAIGN_ROUNDS ?= 3
campaign-bench:
	$(PY) tools/campaign_bench.py $(CAMPAIGN_BATCH) $(CAMPAIGN_GENS) \
	    $(CAMPAIGN_ROUNDS)

# Flight-recorder soak (madsim_tpu/obs/flight.py + prof.py): the
# campaign observability certificates — generation-program retraces
# == 1 per cache key across a 3-campaign session (profiler-certified),
# the interleaved cache A/B, flight-recorder on/off bit-identity on
# both drivers, and the campaign Perfetto export from a
# violation-bearing hunt. The smoke defaults below keep `make check`
# fast; FLIGHT_BATCH=4096 FLIGHT_GENS=4 is the FLIGHT_r08.txt scale.
FLIGHT_BATCH ?= 512
FLIGHT_GENS  ?= 3
FLIGHT_TRACE ?= /tmp/flight_campaign_trace.json
flight:
	$(PY) tools/flight_soak.py $(FLIGHT_BATCH) $(FLIGHT_GENS) \
	    $(FLIGHT_TRACE)

# Observability soak (madsim_tpu.obs): obs-off identity at soak scale,
# device-reduced fleet metrics on OBS_SEEDS seeds, the raftlog
# violation shrunk + replayed with the timeline ring and exported as
# Perfetto trace-event JSON, campaign telemetry/persistence, and the
# guided-vs-uniform delta under AFL hit-count bucketing.
OBS_SEEDS ?= 8192
obs-soak:
	$(PY) tools/obs_soak.py $(OBS_SEEDS)

# Storage-fault soak (madsim_tpu disk chaos): disk-faults-off identity
# (layouts + compact + oracle sample), fsync-before-reply raftlog clean
# under crash/partition/torn-write chaos, the lying-fsync positive
# control for check.recovery_safety, and the missing-sync mutant caught
# by the DiskFault-grown guided hunt + shrunk + replayed. 2048 is the
# evidence-artifact scale (STORE_r10.txt). Needs the native oracle.
STORE_SEEDS ?= 2048
store-soak: native
	$(PY) tools/store_soak.py $(STORE_SEEDS)

# Tail-latency soak (madsim_tpu.obs latency): latency-off identity,
# sketch exactness (fleet sketch == exact bucketing, quantiles within
# one bucket), the clean-vs-GrayFailure p99 blowup, the guided SLO hunt
# beating uniform at equal budget, and find->shrink->replay->explain on
# the breach. 2048 is the evidence-artifact scale (LATENCY_r12.txt).
LATENCY_SEEDS ?= 2048
latency-soak:
	$(PY) tools/latency_soak.py $(LATENCY_SEEDS)

# Session-start TPU capture: the TPU tunnel historically wedges
# mid-session, so grab the round's accelerator numbers FIRST (same
# schema as the driver's end-of-round bench.py artifact). bench.py
# itself also does a staged retry after its CPU pass.
SNAPSHOT ?= BENCH_TPU_snapshot.jsonl
bench-tpu-snapshot:
	$(PY) bench.py > $(SNAPSHOT)
	@tail -1 $(SNAPSHOT)

clean:
	$(MAKE) -C native clean
