"""Headline benchmark: simulated-seconds/sec/chip on batched raft election.

Runs the north-star workload from BASELINE.md (config 4 shape): a large
seed batch of 5-node raft leader elections advanced in lockstep by the
XLA-compiled engine, on whatever accelerator the driver provides (one
TPU chip under axon; CPU elsewhere). Prints exactly one JSON line:

    {"metric": "sim_seconds_per_sec_per_chip", "value": N,
     "unit": "sim_s/s/chip", "vs_baseline": N / 200000}

vs_baseline is against the BASELINE.json north-star target of 200,000
simulated-seconds/sec (65,536-seed batch on a TPU v4-8); per-chip
normalization keeps the number comparable across slice sizes.
"""

import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    from madsim_tpu.engine import EngineConfig, make_init, make_run_while
    from madsim_tpu.models import make_raft

    n_seeds = int(os.environ.get("BENCH_SEEDS", "8192"))
    n_steps = int(os.environ.get("BENCH_STEPS", "600"))

    wl = make_raft()
    cfg = EngineConfig(pool_size=128, loss_p=0.02)
    init = make_init(wl, cfg)
    # while-loop runner: stops as soon as every seed halts (no wasted
    # lockstep iterations on the tail); donation reuses the state buffers
    run = jax.jit(make_run_while(wl, cfg, n_steps), donate_argnums=0)

    state = init(np.arange(n_seeds, dtype=np.uint64))
    # warm-up: compile (first TPU compile is slow; cached afterwards)
    out = run(state)
    jax.block_until_ready(out)

    # timed run on a fresh, disjoint seed range
    state = init(np.arange(n_seeds, 2 * n_seeds, dtype=np.uint64))
    t0 = time.perf_counter()
    out = run(state)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0

    sim_seconds = float(np.asarray(out.now, dtype=np.float64).sum() / 1e9)
    n_chips = max(jax.device_count(), 1)
    value = sim_seconds / wall / n_chips
    print(
        json.dumps(
            {
                "metric": "sim_seconds_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "sim_s/s/chip",
                "vs_baseline": round(value / 200_000.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
