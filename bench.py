"""Headline benchmark: simulated-seconds/sec/chip across the BASELINE configs.

Reports all five BASELINE.md benchmark configs plus raftlog (the raft
log-replication family, beyond-BASELINE) and prints the headline JSON
line (raft, the north-star workload) LAST. Every quoted cell is a
multi-second sized dispatch with reported spread (engine/measure.py) on
BOTH platforms; the deliberately single-seed pingpong config is quoted
as latency (wall us per complete sim), not throughput:

    {"metric": "sim_seconds_per_sec_per_chip", "value": N,
     "unit": "sim_s/s/chip", "vs_baseline": N / 200000,
     "platform": "...", "n_seeds": N, "configs": {...}}

vs_baseline is against the BASELINE.json north-star target of 200,000
simulated-seconds/sec (65,536-seed batch); per-chip normalization keeps
the number comparable across slice sizes.

Resilience contract (the driver runs `python bench.py` unattended): the
parent process NEVER initializes jax. Every measurement runs in a child
subprocess under a watchdog timeout, because the container's TPU tunnel
can wedge such that any jax op hangs forever (not fails). A tiny probe
op picks the platform; on TPU init failure or hang everything falls back
to CPU, the platform actually used is recorded in the JSON, and the
process exits 0 no matter what.
"""

import json
import os
import subprocess
import sys
import time

TARGET = 200_000.0  # BASELINE.json north star, sim_s/s

# name -> (n_seeds, max_steps). Steps are run_while caps; the
# runner exits as soon as every seed halts. CPU-fallback seed counts are
# capped so a wedged-tunnel round still finishes within budget.
# The workload factories, engine configs (pool sizes sized to measured
# peak in-flight event counts with zero overflow — raft/broadcast/
# kvchaos 40, microbench/pingpong 32, raftlog 64), seed counts and
# step caps live in
# madsim_tpu.models.BENCH_SPECS, shared with the cross-backend
# determinism artifact (examples/cross_backend_check.py). This mirror
# keeps the parent process jax-free (the resilience contract above):
#   name -> (n_seeds, max_steps)
CONFIGS = {
    "raft": (65536, 600),
    "microbench": (1024, 1100),
    "pingpong": (1, 300),
    "broadcast": (16384, 500),
    "kvchaos": (4096, 900),
    "raftlog": (16384, 4000),
}
# BASELINE.md config 1 specifies the single-seed pingpong on the CPU sim
# runtime — a lone seed cannot amortize accelerator dispatch overhead
CPU_ONLY_CONFIGS = {"pingpong"}
# CPU fallback sizing: seeds are capped by a measured time budget, not a
# fixed count — a tiny calibration batch estimates per-seed cost and the
# child picks the largest power-of-two batch whose single-batch wall is
# ~CPU_CELL_TARGET_S, so the fallback artifact still carries scaling
# information while every measured dispatch stays multi-second
CPU_CELL_TARGET_S = 3.0
CPU_CALIBRATE_SEEDS = 256


def _child_env(platform: str, config: str, n_seeds: int, n_steps: int) -> dict:
    env = dict(os.environ)
    env["BENCH_CHILD"] = config
    env["BENCH_PLATFORM"] = platform
    env["BENCH_SEEDS"] = str(n_seeds)
    env["BENCH_STEPS"] = str(n_steps)
    return env


def _run_child(platform: str, config: str, n_seeds: int, n_steps: int, timeout: float):
    """Run one measurement in a subprocess; return parsed JSON dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(platform, config, n_seeds, n_steps),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"# {config}@{platform}: timeout after {timeout:.0f}s", file=sys.stderr)
        return None
    if proc.returncode != 0:
        tail = (proc.stderr or "")[-500:]
        print(f"# {config}@{platform}: rc={proc.returncode} {tail}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
    return None


def probe_platform(timeout: float) -> tuple[str, str]:
    """Run a tiny op in a subprocess; 'default' if the accelerator works."""
    res = _run_child("default", "probe", 0, 0, timeout)
    if res and res.get("ok"):
        return "default", res.get("platform", "unknown")
    return "cpu", "cpu"


def parent() -> None:
    budget = float(os.environ.get("BENCH_BUDGET", "1500"))
    per_cfg_cap = float(os.environ.get("BENCH_CONFIG_TIMEOUT", "600"))
    # Probe timeout: a healthy accelerator answers the probe op in a few
    # seconds; a wedged tunnel HANGS (not fails), so every second spent
    # waiting is pure wall burned before the CPU fallback starts —
    # BENCH_r05 lost 120 s to exactly this before the staged retry.
    # 45 s is ample for a cold TPU init; override via BENCH_PROBE_TIMEOUT
    # for exotic targets.
    probe_cap = float(os.environ.get("BENCH_PROBE_TIMEOUT", "45"))
    t_start = time.monotonic()  # lint: allow(wall-clock)

    mode, platform = probe_platform(timeout=min(probe_cap, budget / 4))
    print(f"# probe: mode={mode} platform={platform}", file=sys.stderr)

    results = {}

    def sweep(run_mode: str, configs, stop_on_degrade: bool = False) -> str:
        """Run configs under run_mode; returns the (possibly degraded)
        mode. Results overwrite earlier entries for the same config, so
        a successful late TPU retry replaces the CPU fallback number.
        ``stop_on_degrade``: bail out once the accelerator wedges (the
        retry pass — re-running CPU fallbacks would duplicate pass-1
        results for pure budget waste)."""
        cur = run_mode
        for config, (n_seeds, n_steps) in configs:
            if stop_on_degrade and cur == "cpu":
                print(f"# retry degraded, skipping {config}", file=sys.stderr)
                continue
            remaining = budget - (time.monotonic() - t_start)  # lint: allow(wall-clock)
            if remaining < 60 and results:
                print(f"# budget exhausted, skipping {config}", file=sys.stderr)
                continue
            timeout = max(90.0, min(per_cfg_cap, remaining))
            cfg_mode = "cpu" if config in CPU_ONLY_CONFIGS else cur
            res = _run_child(cfg_mode, config, n_seeds, n_steps, timeout)
            if res is None and cfg_mode == "default":
                # accelerator wedged mid-run: degrade this + later configs
                cur = "cpu"
                remaining = budget - (time.monotonic() - t_start)  # lint: allow(wall-clock)
                if config not in results:  # keep any prior (TPU) result
                    res = _run_child(
                        "cpu", config, n_seeds, n_steps,
                        max(90.0, min(per_cfg_cap, remaining)),
                    )
            if res is not None and res.get("error"):
                # a config-level failure (e.g. pool overflow), not a
                # wedge: surface it, move on, don't degrade the platform
                print(json.dumps(res), flush=True)
                print(f"# {config}: {res['error']}", file=sys.stderr)
            elif res is not None:
                results[config] = res
                print(json.dumps(res), flush=True)
        return cur

    mode = sweep(mode, CONFIGS.items())
    platform = "cpu" if mode == "cpu" else platform

    # Staged retry: the tunnel historically wedges transiently. If the
    # accelerator was unavailable (at probe time or mid-sweep), re-probe
    # after the CPU pass and re-measure the accelerator configs — fresh
    # runs only, never a replay of stale numbers.
    remaining = budget - (time.monotonic() - t_start)  # lint: allow(wall-clock)
    if mode == "cpu" and remaining > 180:
        retry_mode, retry_platform = probe_platform(
            timeout=min(probe_cap, remaining / 3)
        )
        print(
            f"# staged retry probe: mode={retry_mode} platform={retry_platform}",
            file=sys.stderr,
        )
        if retry_mode == "default":
            accel_cfgs = [
                (c, v)
                for c, v in CONFIGS.items()
                if c not in CPU_ONLY_CONFIGS
                and results.get(c, {}).get("platform", "cpu") == "cpu"
            ]
            final_mode = sweep("default", accel_cfgs, stop_on_degrade=True)
            if final_mode == "default":
                platform = retry_platform

    summary = summary_dict(results, platform)
    if summary.get("platform") == "cpu":
        banked = _banked_tpu_headline()
        if banked is not None:
            # the tunnel wedged for THIS run, but a real-silicon headline
            # was banked earlier by tools/tpu_chain.sh — surface it,
            # clearly labeled as a prior measurement with its artifact
            summary["banked_tpu_headline"] = banked
    print(json.dumps(summary), flush=True)


def _banked_tpu_headline() -> dict | None:
    """Newest RAFT_TPU_*.json banked by the watcher chain, if any —
    attached to CPU-fallback summaries so a wedged tunnel at measurement
    time does not hide the round's real-silicon number."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(here, "RAFT_TPU_*.json"))
    if not paths:
        return None
    newest = max(paths, key=os.path.getmtime)
    age_h = (time.time() - os.path.getmtime(newest)) / 3600.0  # lint: allow(wall-clock)
    if age_h > 48.0:
        # a rounds-old artifact describes a different engine; don't
        # present it as this round's number
        return None
    try:
        with open(newest) as f:
            row = json.loads(f.read().strip().splitlines()[-1])
        if row.get("platform") == "cpu":
            return None
        return {
            "note": "prior real-TPU measurement banked by tools/tpu_chain.sh; "
                    "this run's tunnel was unavailable",
            "artifact": os.path.basename(newest),
            "value": row.get("value"),
            "unit": row.get("unit"),
            "n_seeds": row.get("n_seeds"),
            "spread_pct": row.get("spread_pct"),
            "vs_baseline": round(float(row["value"]) / TARGET, 4),
        }
    except (OSError, ValueError, IndexError, KeyError, TypeError):
        return None


def summary_dict(results: dict, platform: str) -> dict:
    """The one parent summary line every bench artifact ends with —
    consumers ``tail -1`` for the headline (raft) value + vs_baseline.
    Shared by the live parent sweep and the row-assembly mode so
    chain-assembled artifacts carry the identical schema."""
    head = results.get("raft")
    value = float(head["value"]) if head else 0.0
    return {
        "metric": "sim_seconds_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "sim_s/s/chip",
        "vs_baseline": round(value / TARGET, 4),
        "platform": head.get("platform", platform) if head else platform,
        "n_seeds": int(head["n_seeds"]) if head else 0,
        "configs": {
            k: {
                "value": v["value"],
                "unit": v.get("unit", "sim_s/s/chip"),
                "n_seeds": v["n_seeds"],
                "platform": v.get("platform", platform),
                "spread_pct": v.get("spread_pct"),
            }
            for k, v in results.items()
        },
    }


def assemble(row_paths: str) -> None:
    """BENCH_ASSEMBLE mode: build a full-bench artifact from per-config
    row files banked by tools/tpu_chain.sh (name=path,name=path,...).
    Emits the child rows in CONFIGS order, then the parent summary
    line, to stdout."""
    paths = dict(item.split("=", 1) for item in row_paths.split(","))
    unknown = set(paths) - set(CONFIGS)
    if unknown:
        raise SystemExit(f"BENCH_ASSEMBLE: unknown configs {sorted(unknown)}")
    missing = set(CONFIGS) - set(paths)
    if missing:
        raise SystemExit(f"BENCH_ASSEMBLE: missing configs {sorted(missing)}")
    results = {}
    for name in CONFIGS:
        with open(paths[name]) as f:
            row = json.loads(f.read().strip().splitlines()[-1])
        if row.get("config") != name:
            raise SystemExit(
                f"BENCH_ASSEMBLE: {paths[name]} holds config "
                f"{row.get('config')!r}, expected {name!r}"
            )
        results[name] = row
        print(json.dumps(row))
    print(json.dumps(summary_dict(results, results["raft"]["platform"])), flush=True)


# ---------------------------------------------------------------- child


def child(config: str) -> None:
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    try:  # persistent cache: amortize XLA compiles across child processes
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    if config == "probe":
        import jax.numpy as jnp

        d = jax.devices()[0]
        x = jnp.arange(8.0)
        jax.block_until_ready(x @ x)
        print(json.dumps({"ok": True, "platform": d.platform}))
        return

    import numpy as np

    from madsim_tpu.engine import (
        EngineConfig,
        make_init,
        make_run_compacted,
        time32_eligible,
    )
    from madsim_tpu.models import BENCH_SPECS

    n_seeds = int(os.environ.get("BENCH_SEEDS", "8192"))
    n_steps = int(os.environ.get("BENCH_STEPS", "600"))
    if config not in BENCH_SPECS:
        raise SystemExit(f"unknown config {config}")
    factory, cfg_kwargs, _spec_seeds, _spec_steps = BENCH_SPECS[config]
    wl, cfg = factory(), EngineConfig(**cfg_kwargs)

    # int32 event times whenever the (workload, config) bounds allow:
    # a value-identical lowering (test-pinned against int64), already
    # the accelerator default, and measured ~8% faster on CPU too —
    # the bench quotes the engine's fastest value-identical program,
    # exactly as it does for layout and compaction
    t32 = True if time32_eligible(wl, cfg) else None
    init = make_init(wl, cfg, time32=t32)

    # one min_size policy for BOTH platforms, so a config's accelerator
    # and CPU numbers describe the same compaction program
    def _min_size(s: int) -> int:
        return min(2048, max(s // 4, 1))

    from madsim_tpu.engine.measure import measure_throughput

    accel = jax.devices()[0].platform != "cpu"
    n_chips = max(jax.device_count(), 1)
    # seeds wrap inside the range each pool size was verified
    # overflow-free for (models.BENCH_SPECS sizing note): raft over
    # 0..524287, the rest over the sweep's 0..131071
    seed_mod = 524288 if config == "raft" else 131072

    if config == "pingpong":
        # BASELINE config 1 is a deliberately single-seed sim — one seed
        # cannot amortize dispatch overhead, so a throughput quote would
        # measure the transport. Quote it as LATENCY (engine/measure.py
        # measure_latency: repeats independent single-seed sims packed
        # into multi-second dispatches, median wall-per-sim).
        from madsim_tpu.engine.measure import measure_latency

        rec = measure_latency(wl, cfg, n_steps, seed_mod=seed_mod, time32=t32)
        if rec["overflow"] or not rec["all_halted"]:
            print(
                json.dumps(
                    {
                        "config": config,
                        "error": "pool_overflow"
                        if rec["overflow"]
                        else "not_all_halted",
                        "drops": rec["overflow"],
                    }
                )
            )
            return
        print(
            json.dumps(
                {
                    "config": config,
                    "metric": "wall_us_per_sim",
                    "value": rec["wall_us_per_sim_median"],
                    "unit": "us/sim",
                    "platform": jax.devices()[0].platform,
                    "n_seeds": 1,
                    "repeats_per_dispatch": rec["repeats"],
                    "dispatch_walls_s": rec["dispatch_walls_s"],
                    "spread_pct": rec["spread_pct"],
                    "sim_s_per_s": rec["sim_s_per_s"],
                }
            )
        )
        return

    if not accel and n_seeds > CPU_CALIBRATE_SEEDS:
        # CPU fallback sizing: estimate per-seed cost on a small batch,
        # then pick the largest power-of-two batch whose single-batch
        # wall is ~CPU_CELL_TARGET_S (capped at the spec seed count) —
        # measure_throughput then packs repeats if the batch is shorter
        run = make_run_compacted(
            wl, cfg, n_steps, time32=t32,
            min_size=_min_size(CPU_CALIBRATE_SEEDS), fields=("now",),
        )
        jax.block_until_ready(
            run.compute(init(np.arange(CPU_CALIBRATE_SEEDS, dtype=np.uint64)))
        )  # compile outside the timed window
        cal = init(np.arange(CPU_CALIBRATE_SEEDS, dtype=np.uint64))
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(run.compute(cal))
        per_seed = (time.perf_counter() - t0) / CPU_CALIBRATE_SEEDS  # lint: allow(wall-clock)
        fit = int(CPU_CELL_TARGET_S / max(per_seed, 1e-9))
        sized = CPU_CALIBRATE_SEEDS
        while sized * 2 <= min(fit, n_seeds):
            sized *= 2
        n_seeds = sized

    # Both platforms: jitter-proof sized dispatches (engine/measure.py).
    # The TPU tunnel has multi-100ms dispatch jitter; the CPU has none
    # but multi-second cells with reported spread cost little and keep
    # the artifact schema identical across platforms. Each dispatch
    # packs `repeats` independent seed-batches into one jitted
    # fori_loop >= target_wall_s long; the quoted rate is the median
    # over n_measure dispatches.
    rec = measure_throughput(
        wl, cfg, n_steps, n_seeds,
        target_wall_s=5.0 if accel else 3.5,
        n_measure=5 if accel else 3,
        seed_mod=seed_mod, min_size=_min_size(n_seeds), time32=t32,
    )
    # the small pool sizes are only valid while nothing overflows; a
    # silent drop would skew the metric. Reported as a distinct
    # JSON error (exit 0) so the parent records a config failure
    # instead of misreading rc!=0 as a wedge and degrading to CPU.
    if rec["overflow"]:
        print(
            json.dumps(
                {"config": config, "error": "pool_overflow", "drops": rec["overflow"]}
            )
        )
        return
    print(
        json.dumps(
            {
                "config": config,
                "metric": "sim_seconds_per_sec_per_chip",
                "value": round(rec["sim_s_per_s_median"] / n_chips, 2),
                "unit": "sim_s/s/chip",
                "platform": jax.devices()[0].platform,
                "n_seeds": n_seeds,
                "repeats_per_dispatch": rec["repeats"],
                "dispatch_walls_s": rec["dispatch_walls_s"],
                "spread_pct": rec["spread_pct"],
                "all_halted": rec["all_halted"],
            }
        )
    )


def main() -> None:
    rows = os.environ.get("BENCH_ASSEMBLE")
    if rows:
        assemble(rows)
        return
    config = os.environ.get("BENCH_CHILD")
    if config:
        child(config)
        return
    try:
        parent()
    except Exception as exc:  # never hand the driver an empty artifact
        print(f"# bench parent error: {exc!r}", file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "sim_seconds_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "sim_s/s/chip",
                    "vs_baseline": 0.0,
                    "platform": "error",
                    "n_seeds": 0,
                    "configs": {},
                }
            )
        )


if __name__ == "__main__":
    main()
