"""Raw asyncio streams over the simulated network (net/aio_streams.py).

``asyncio.start_server`` / ``asyncio.open_connection`` — the stdlib's
own StreamReader/StreamWriter machinery — running against NetSim via
the interposed loop's create_server/create_connection. The analog of
the reference simulating tokio's TcpStream under the unchanged API
(sim/net/tcp/stream.rs).
"""

import asyncio
import os

import pytest

import madsim_tpu as ms
from madsim_tpu.runtime.builder import Builder


def run_sim(workload, seed=7):
    b = Builder()
    b.seed = seed
    b.count = 1
    # honor the determinism re-check tier (make determinism)
    b.check_determinism = bool(os.environ.get("MADSIM_TEST_CHECK_DETERMINISM"))
    return b.run(workload)


def _echo_cluster():
    """Returns (main coroutine fn, transcript list). Pure-stdlib echo
    server + client; only the node scaffolding touches ms APIs."""
    transcript = []

    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    writer.write(b"echo:" + line)
                    await writer.drain()
                writer.close()
                await writer.wait_closed()

            server = await asyncio.start_server(on_client, "10.0.0.1", 8000)
            async with server:
                await server.serve_forever()

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.05)
            reader, writer = await asyncio.open_connection("10.0.0.1", 8000)
            for i in range(3):
                writer.write(f"msg{i}\n".encode())
                await writer.drain()
                line = await reader.readline()
                transcript.append((line, ms.now_ns()))
            writer.write_eof()
            tail = await reader.read()
            writer.close()
            return tail

        return await cli.spawn(client())

    return main, transcript


def test_stdlib_echo_over_sim_net():
    main, transcript = _echo_cluster()
    tail = run_sim(main)
    assert tail == b""
    # under MADSIM_TEST_CHECK_DETERMINISM the builder replays the sim,
    # so the closure records the transcript once per replay — and the
    # replays must be identical
    assert len(transcript) % 3 == 0 and transcript
    first, rest = transcript[:3], transcript[3:]
    for i in range(0, len(rest), 3):
        assert rest[i:i + 3] == first, "replay diverged"
    assert [line for line, _t in first] == [
        b"echo:msg0\n", b"echo:msg1\n", b"echo:msg2\n"
    ]
    # each round trip took real simulated network time
    times = [t for _line, t in first]
    assert times == sorted(times) and times[0] > 50_000_000


def test_stdlib_echo_is_deterministic():
    main1, t1 = _echo_cluster()
    main2, t2 = _echo_cluster()
    main3, t3 = _echo_cluster()
    run_sim(main1, seed=21)
    run_sim(main2, seed=21)
    run_sim(main3, seed=22)
    assert t1 == t2, "same seed: identical transcript incl. timestamps"
    assert t1 != t3, "different seed: different network timings"


def test_concurrent_clients_and_peername():
    async def main():
        h = ms.Handle.current()
        peers = []

        async def serve():
            async def on_client(reader, writer):
                peers.append(writer.get_extra_info("peername"))
                data = await reader.readline()
                writer.write(data.upper())
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(on_client, "10.0.0.1", 9000)
            async with server:
                await server.serve_forever()

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()

        async def one(i):
            await asyncio.sleep(0.01)
            r, w = await asyncio.open_connection("10.0.0.1", 9000)
            w.write(f"hello-{i}\n".encode())
            await w.drain()
            out = await r.readline()
            w.close()
            return out

        outs = []
        for i in range(3):
            node = h.create_node().name(f"c{i}").ip(f"10.0.0.{i + 2}").build()
            outs.append(node.spawn(one(i)))
        return [await o for o in outs], peers

    outs, peers = run_sim(main)
    assert sorted(outs) == [b"HELLO-0\n", b"HELLO-1\n", b"HELLO-2\n"]
    assert sorted(ip for ip, _port in peers) == [
        "10.0.0.2", "10.0.0.3", "10.0.0.4"
    ]


def test_server_node_kill_resets_client_stream():
    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                writer.write(b"hi\n")
                await writer.drain()
                await reader.read()  # hold the connection open

            server = await asyncio.start_server(on_client, "10.0.0.1", 9100)
            async with server:
                await server.serve_forever()

        srv = h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            reader, writer = await asyncio.open_connection("10.0.0.1", 9100)
            first = await reader.readline()
            h.kill(srv)
            # the killed peer's stream drains to EOF (reset semantics,
            # tcp/mod.rs:98-208)
            rest = await reader.read()
            writer.close()
            return first, rest

        return await cli.spawn(client())

    first, rest = run_sim(main)
    assert first == b"hi\n"
    assert rest == b""


def test_readexactly_and_readuntil():
    # the rest of the StreamReader surface over the simulated TCP
    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                writer.write(b"HDR|12345678world")
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(on_client, "10.0.0.1", 9600)
            async with server:
                await server.serve_forever()

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            reader, writer = await asyncio.open_connection("10.0.0.1", 9600)
            hdr = await reader.readuntil(b"|")
            body = await reader.readexactly(8)
            rest = await reader.read()
            with pytest.raises(asyncio.IncompleteReadError):
                await reader.readexactly(5)  # stream already at EOF
            writer.close()
            return hdr, body, rest

        return await cli.spawn(client())

    hdr, body, rest = run_sim(main)
    assert (hdr, body, rest) == (b"HDR|", b"12345678", b"world")


def test_half_close_request_response():
    # write_eof as the request delimiter: the server reads to EOF, then
    # RESPONDS over the still-open write side (eof_received() -> True
    # keeps the transport alive — real TCP half-close)
    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                req = await reader.read()  # to client's EOF
                writer.write(b"resp:" + req)
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(on_client, "10.0.0.1", 9200)
            async with server:
                await server.serve_forever()

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            reader, writer = await asyncio.open_connection("10.0.0.1", 9200)
            writer.write(b"the-request")
            writer.write_eof()
            resp = await reader.read()
            writer.close()
            return resp

        return await cli.spawn(client())

    assert run_sim(main) == b"resp:the-request"


def test_server_close_wakes_serve_forever():
    # real asyncio Server.close cancels the serve-forever future; the
    # awaiting task must wake instead of pending forever (which would
    # DeadlockError the sim if it were the last runnable work)
    async def main2():
        server = await asyncio.start_server(lambda r, w: None, "10.0.0.9", 9301)

        async def closer():
            await asyncio.sleep(0.05)
            server.close()

        asyncio.create_task(closer())
        with pytest.raises(asyncio.CancelledError):
            async with server:
                await server.serve_forever()
        return "woke"

    assert run_sim(main2) == "woke"


def test_write_after_eof_raises():
    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                await reader.read()

            server = await asyncio.start_server(on_client, "10.0.0.1", 9400)
            async with server:
                await server.serve_forever()

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            _r, writer = await asyncio.open_connection("10.0.0.1", 9400)
            writer.write_eof()
            with pytest.raises(RuntimeError, match="write_eof"):
                writer.write(b"too late")
            writer.close()
            return "ok"

        return await cli.spawn(client())

    assert run_sim(main) == "ok"


def test_connect_by_node_name():
    # the node registry is the zone file: raw open_connection by NAME
    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                writer.write(b"named\n")
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(on_client, "10.0.0.1", 7500)
            async with server:
                await server.serve_forever()

        h.create_node().name("kv-server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            reader, writer = await asyncio.open_connection("kv-server", 7500)
            out = await reader.readline()
            writer.close()
            # an unknown name fails like a real resolver
            with pytest.raises(OSError, match="resolution failed"):
                await asyncio.open_connection("no-such-host", 1)
            # loop.getaddrinfo resolves too
            infos = await asyncio.get_running_loop().getaddrinfo(
                "kv-server", 7500
            )
            return out, infos[0][4]

        return await cli.spawn(client())

    out, addr = run_sim(main)
    assert out == b"named\n"
    assert addr == ("10.0.0.1", 7500)


def test_clog_stalls_and_resumes_raw_stream():
    # a clogged link stalls the byte stream (bytes wait, nothing drops)
    # and delivery resumes after unclog — net/mod.rs:157-216 semantics
    # observed through UNMODIFIED asyncio stream code
    from madsim_tpu.net import NetSim

    async def main():
        h = ms.Handle.current()

        async def serve():
            async def on_client(reader, writer):
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    writer.write(b"ack:" + line)
                    await writer.drain()

            server = await asyncio.start_server(on_client, "10.0.0.1", 9500)
            async with server:
                await server.serve_forever()

        srv = h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            reader, writer = await asyncio.open_connection("10.0.0.1", 9500)
            writer.write(b"one\n")
            await writer.drain()
            assert await reader.readline() == b"ack:one\n"

            net = NetSim.current()
            net.clog_link(cli.id, srv.id)
            t_clog = ms.now_ns()
            writer.write(b"two\n")
            await writer.drain()
            # the request is stalled: the ack cannot arrive while the
            # link is clogged (clog is set for 2 full seconds).
            # asyncio.TimeoutError: pre-3.11, wait_for raises the asyncio
            # exception, which is NOT the builtin TimeoutError yet
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.readline(), timeout=2.0)
            net.unclog_link(cli.id, srv.id)
            ack = await reader.readline()
            waited_ns = ms.now_ns() - t_clog
            writer.close()
            return ack, waited_ns

        return await cli.spawn(client())

    ack, waited_ns = run_sim(main)
    assert ack == b"ack:two\n", "no bytes may be lost across a clog"
    assert waited_ns >= 2_000_000_000, "delivery only after the clog window"


def test_raw_datagram_endpoint_over_sim_udp():
    # stdlib DatagramProtocol classes over the simulated UDP
    # (loop.create_datagram_endpoint -> net/aio_streams.py)
    class EchoServer(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport

        def datagram_received(self, data, addr):
            self.transport.sendto(b"echo:" + data, addr)

    class Client(asyncio.DatagramProtocol):
        def __init__(self):
            self.got = asyncio.Queue()

        def connection_made(self, transport):
            self.transport = transport

        def datagram_received(self, data, addr):
            self.got.put_nowait(data)

    async def main():
        h = ms.Handle.current()

        async def serve():
            loop = asyncio.get_running_loop()
            await loop.create_datagram_endpoint(
                EchoServer, local_addr=("10.0.0.1", 5300)
            )
            await asyncio.sleep(1000)

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            loop = asyncio.get_running_loop()
            tr, proto = await loop.create_datagram_endpoint(
                Client,
                local_addr=("10.0.0.2", 0),
                remote_addr=("10.0.0.1", 5300),
            )
            # connected-socket sendto with a FOREIGN address must raise
            with pytest.raises(ValueError, match="connected"):
                tr.sendto(b"x", ("10.9.9.9", 1))
            out = []
            for i in range(3):
                tr.sendto(f"dgram{i}".encode())
                out.append(await proto.got.get())
            tr.close()
            return out

        return await cli.spawn(client())

    out = run_sim(main)
    assert out == [b"echo:dgram0", b"echo:dgram1", b"echo:dgram2"]


def test_raw_datagrams_see_packet_loss():
    # datagrams ride the loss model (tcp-like pipes do NOT — they are
    # the reliable abstraction): under 40% loss some sendto's vanish,
    # deterministically per seed
    from madsim_tpu.runtime import Config, NetConfig

    class Server(asyncio.DatagramProtocol):
        def __init__(self, got):
            self.got = got

        def connection_made(self, transport):
            pass

        def datagram_received(self, data, addr):
            self.got.append(data)

    async def main():
        h = ms.Handle.current()
        got: list = []

        async def serve():
            loop = asyncio.get_running_loop()
            await loop.create_datagram_endpoint(
                lambda: Server(got), local_addr=("10.0.0.1", 5700)
            )
            await asyncio.sleep(1000)

        h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.02)
            loop = asyncio.get_running_loop()
            tr, _p = await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                local_addr=("10.0.0.2", 0),
                remote_addr=("10.0.0.1", 5700),
            )
            for i in range(50):
                tr.sendto(f"d{i}".encode())
                await asyncio.sleep(0.005)
            await asyncio.sleep(0.5)
            tr.close()
            return len(got)

        return await cli.spawn(client())

    cfg = Config()
    cfg.net = NetConfig()
    cfg.net.packet_loss_rate = 0.4

    def run_lossy(seed):
        b = Builder()
        b.seed = seed
        b.count = 1
        b.config = cfg
        return b.run(main)

    n1, n2, n3 = run_lossy(5), run_lossy(5), run_lossy(6)
    assert n1 == n2, "same seed must drop the same datagrams"
    assert 5 <= n1 < 50, f"40% loss should drop some of 50 ({n1} arrived)"
    assert n1 != n3 or True  # different seeds usually differ; no hard claim


def test_datagram_endpoint_failed_resolve_releases_port():
    async def main():
        loop = asyncio.get_running_loop()
        with pytest.raises(OSError, match="resolution failed"):
            await loop.create_datagram_endpoint(
                asyncio.DatagramProtocol,
                local_addr=("10.0.0.1", 5555),
                remote_addr=("no-such-host", 1),
            )
        # the bind must have been released: same port works again
        tr, _p = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("10.0.0.1", 5555)
        )
        tr.close()
        return "ok"

    assert run_sim(main) == "ok"


def test_datagram_sendto_validates_at_call_site():
    async def main():
        loop = asyncio.get_running_loop()
        tr, _p = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("10.0.0.1", 5600)
        )
        # malformed addr raises HERE, not in the background pump (where
        # it would fail the whole sim far from the bug)
        with pytest.raises(ValueError):
            tr.sendto(b"x", "10.0.0.2")  # no port
        tr.close()
        return "ok"

    assert run_sim(main) == "ok"


def test_getaddrinfo_none_host_is_wildcard():
    async def main():
        infos = await asyncio.get_running_loop().getaddrinfo(None, 8080)
        return infos[0][4]

    assert run_sim(main) == ("0.0.0.0", 8080)


def test_unretrieved_task_exception_reported_at_sim_end(capsys):
    async def main():
        async def boom():
            raise ValueError("silent-boom")

        asyncio.create_task(boom())
        await asyncio.sleep(0.05)
        return "done"

    assert run_sim(main) == "done"
    err = capsys.readouterr().err
    assert "unretrieved exception" in err and "silent-boom" in err


def test_retrieved_task_exception_not_reported(capsys):
    async def main():
        async def boom():
            raise ValueError("seen-boom")

        t = asyncio.create_task(boom())
        await asyncio.sleep(0.05)
        with pytest.raises(ValueError):
            await t
        return "done"

    assert run_sim(main) == "done"
    assert "unretrieved" not in capsys.readouterr().err
