"""Chaos-schedule search (engine/search.py): batched invariant sweeps
with per-seed repro — the engine-scale multi-seed runner
(builder.rs:110-148 analog, BASELINE.md config 5)."""

import numpy as np
import pytest

from madsim_tpu.engine import EngineConfig, search_seeds
from madsim_tpu.models import make_kvchaos, make_microbench, make_raft


def test_healthy_workload_has_no_violations():
    wl = make_raft()
    cfg = EngineConfig(pool_size=48, loss_p=0.02)
    # invariant: some node won the election (role LEADER == 2)
    report = search_seeds(
        wl, cfg,
        invariant=lambda v: (v["node_state"][:, :, 0] == 2).any(axis=1),
        n_seeds=256, max_steps=600,
    )
    assert report.failing_seeds.size == 0
    assert report.unhalted_seeds.size == 0
    assert "0 violation(s)" in report.banner()


def test_search_finds_planted_violations_deterministically():
    wl = make_kvchaos(writes=5)
    cfg = EngineConfig(pool_size=48, loss_p=0.02)
    # a deliberately-too-strong invariant: every replica APPLIED at
    # least `writes` REPL messages. Replicas are RAM-only, so a chaos
    # kill wipes the victim's apply counter mid-stream and the re-sync
    # only replays the current write — the planted "bug" the search
    # must dig out (schedules whose kill lands early/never pass).
    def all_replicas_current(v):
        ns = v["node_state"]
        return (ns[:, 1:5, 1] >= 5).all(axis=1)

    r1 = search_seeds(wl, cfg, all_replicas_current, n_seeds=512, max_steps=900)
    r2 = search_seeds(wl, cfg, all_replicas_current, n_seeds=512, max_steps=900)
    # deterministic: the same seeds fail every run
    assert np.array_equal(r1.failing_seeds, r2.failing_seeds)
    assert r1.failing_seeds.size > 0, "chaos should break the too-strong invariant"
    assert r1.failing_seeds.size < 512, "most schedules still satisfy it"
    assert f"{r1.failing_seeds.size} violation(s)" in r1.banner()
    assert "config_hash=" + cfg.hash() in r1.banner()


def test_failing_seed_reproduces_in_isolation():
    wl = make_kvchaos(writes=5)
    cfg = EngineConfig(pool_size=48, loss_p=0.02)

    def all_replicas_current(v):
        return (v["node_state"][:, 1:5, 1] >= 5).all(axis=1)

    batch = search_seeds(wl, cfg, all_replicas_current, n_seeds=512, max_steps=900)
    bad = int(batch.failing_seeds[0])
    # rerun the one failing seed alone: same verdict, same trace hash
    solo = search_seeds(
        wl, cfg, all_replicas_current,
        n_seeds=1, max_steps=900, seed_base=bad,
    )
    assert solo.failing_seeds.tolist() == [bad]
    batch_trace = batch.traces[list(batch.seeds).index(bad)]
    assert int(solo.traces[0]) == int(batch_trace)


def test_invariant_shape_is_validated():
    # arg-validation only — the cheapest model body suffices (compiling
    # the raft search program here cost 6 s cold for a ValueError)
    wl = make_microbench(rounds=5)
    cfg = EngineConfig(pool_size=8)
    with pytest.raises(ValueError, match="boolean array"):
        search_seeds(wl, cfg, lambda v: np.bool_(True), n_seeds=8, max_steps=50)


def test_overflowed_seeds_are_flagged_not_reported():
    # a pool too small for the workload drops events: those seeds'
    # verdicts are simulator artifacts, so they're quarantined in
    # overflowed_seeds instead of reported as violations
    wl = make_raft()
    cfg = EngineConfig(pool_size=8, loss_p=0.02)
    report = search_seeds(
        wl, cfg,
        invariant=lambda v: (v["node_state"][:, :, 0] == 2).any(axis=1),
        n_seeds=64, max_steps=600,
    )
    assert report.overflowed_seeds.size > 0
    assert not (set(report.failing_seeds) & set(report.overflowed_seeds))
    assert "overflowed the event pool" in report.banner()


def test_search_reuses_compiled_run():
    from madsim_tpu.engine import search

    wl = make_raft()
    cfg = EngineConfig(pool_size=48, loss_p=0.02)
    inv = lambda v: (v["node_state"][:, :, 0] == 2).any(axis=1)  # noqa: E731
    before = len(search._RUN_CACHE)
    search_seeds(wl, cfg, inv, n_seeds=32, max_steps=200)
    search_seeds(wl, cfg, inv, n_seeds=32, max_steps=200)
    assert len(search._RUN_CACHE) == before + 1


@pytest.mark.slow
def test_compact_search_same_verdicts_and_traces():
    # compact=True runs the seed-compaction path: identical per-seed
    # verdicts and trace hashes, narrower view (node_state etc. only)
    wl = make_kvchaos(writes=5)
    cfg = EngineConfig(pool_size=48, loss_p=0.02)

    def all_replicas_current(v):
        return (np.asarray(v["node_state"])[:, 1:5, 1] >= 5).all(axis=1)

    full = search_seeds(wl, cfg, all_replicas_current, n_seeds=256, max_steps=900)
    fast = search_seeds(
        wl, cfg, all_replicas_current, n_seeds=256, max_steps=900, compact=True
    )
    assert np.array_equal(full.failing_seeds, fast.failing_seeds)
    assert np.array_equal(full.traces, fast.traces)
    assert np.array_equal(full.halted, fast.halted)
