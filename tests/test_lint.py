"""madsim_tpu.lint: the jaxpr taint walker, the non-interference proof
over the engine, and the AST nondeterminism-leak linter."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from madsim_tpu.engine import (
    DERIVED_STATE_FIELDS,
    STORAGE_STATE_FIELDS,
    EngineConfig,
    Workload,
    core_fields,
    derived_fields,
    make_init,
    make_run_while,
    user_kind,
)
from madsim_tpu.engine.core import MET_SYNC, MET_SYNC_LOST, KIND_SYNC_OK
from madsim_tpu.lint import (
    analyze_jaxpr,
    check_noninterference,
    lint_repo,
    lint_source,
    model_matrix,
    plant_met_leak,
)
from madsim_tpu.models import make_raft, make_raftlog

CFG = EngineConfig(pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
CFG_RL = EngineConfig(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)


def _taints(closed, **by_index):
    n = len(closed.jaxpr.invars)
    out = [frozenset() for _ in range(n)]
    for i, label in by_index.items():
        out[int(i)] = frozenset({label})
    return out


class TestTaintWalker:
    """The walker on hand-built jaxprs — every control construct the
    engine's step/run functions route taint through."""

    def test_straight_line_chain(self):
        def f(x, y):
            return x + 1.0, y * 2.0, x * y

        closed = jax.make_jaxpr(f)(1.0, 2.0)
        res = analyze_jaxpr(closed, [frozenset({"x"}), frozenset()])
        assert res.out_taint[0] == {"x"}
        assert res.out_taint[1] == frozenset()
        assert res.out_taint[2] == {"x"}
        # every tainted equation is on the frontier; x*y mixes clean
        assert any(r.mixes_clean for r in res.frontier)

    def test_multiply_by_zero_still_flows(self):
        # the planted-mutant shape: value-identical, data-dependent —
        # the edge bit-identity tests can never see
        def f(x, y):
            return y + x * 0.0

        closed = jax.make_jaxpr(f)(1.0, 2.0)
        res = analyze_jaxpr(closed, [frozenset({"x"}), frozenset()])
        assert res.out_taint[0] == {"x"}

    def test_scan_carried_taint(self):
        # taint enters the carry from xs on iteration 1 and must stick:
        # only the fixpoint sees it
        def f(c, xs):
            def body(carry, x):
                return carry + x, carry

            return lax.scan(body, c, xs)

        closed = jax.make_jaxpr(f)(0.0, jnp.arange(3.0))
        res = analyze_jaxpr(closed, _taints(closed, **{"1": "xs"}))
        out_c, out_ys = res.out_taint
        assert out_c == {"xs"}
        assert out_ys == {"xs"}  # ys emit the carry, tainted from iter 2
        # clean xs, tainted init carry: both outputs taste the carry
        res2 = analyze_jaxpr(closed, _taints(closed, **{"0": "c0"}))
        assert res2.out_taint[0] == {"c0"}
        assert res2.out_taint[1] == {"c0"}

    def test_cond_branch_join(self):
        def f(p, a, b):
            return lax.cond(p, lambda o: o[0] + 1.0, lambda o: o[1], (a, b))

        closed = jax.make_jaxpr(f)(True, 1.0, 2.0)
        # taint only the UNTAKEN-in-spirit branch operand: joins anyway
        res = analyze_jaxpr(closed, _taints(closed, **{"2": "b"}))
        assert res.out_taint[0] == {"b"}
        # implicit flow: a tainted predicate taints every output
        res2 = analyze_jaxpr(closed, _taints(closed, **{"0": "pred"}))
        assert "pred" in res2.out_taint[0]

    def test_while_implicit_flow(self):
        # the loop bound is tainted: the iteration count observes it,
        # so the carried value is tainted even though no arithmetic
        # touches the bound
        def f(n, x):
            def cond(c):
                return c[0] < n

            def body(c):
                return (c[0] + 1, c[1] * 2.0)

            return lax.while_loop(cond, body, (0, x))

        closed = jax.make_jaxpr(f)(3, 1.0)
        res = analyze_jaxpr(closed, _taints(closed, **{"0": "n"}))
        assert "n" in res.out_taint[1]

    def test_pjit_boundary(self):
        @jax.jit
        def inner(a, b):
            return a + b, b - 1.0

        def f(a, b):
            return inner(a, b)

        closed = jax.make_jaxpr(f)(1.0, 2.0)
        assert any(e.primitive.name == "pjit" for e in closed.jaxpr.eqns)
        res = analyze_jaxpr(closed, _taints(closed, **{"0": "a"}))
        assert res.out_taint[0] == {"a"}
        assert res.out_taint[1] == frozenset()
        # the frontier path names the nested location
        assert any("pjit" in r.path for r in res.frontier)

    def test_shard_map_boundary(self):
        # the multi-chip call boundary: labels must cross POSITIONALLY
        # (output 1 stays clean), not smear conservatively over every
        # output, and the frontier path names the nested location
        from jax.sharding import PartitionSpec as P

        from madsim_tpu.parallel import make_mesh, shard_map_nocheck

        mesh = make_mesh()
        ax = mesh.axis_names

        def body(a, b):
            return a + b, b * 2.0

        f = shard_map_nocheck(
            body, mesh, in_specs=(P(ax), P(ax)), out_specs=(P(ax), P(ax))
        )
        closed = jax.make_jaxpr(f)(jnp.zeros(8), jnp.ones(8))
        assert any(
            e.primitive.name == "shard_map" for e in closed.jaxpr.eqns
        )
        res = analyze_jaxpr(closed, _taints(closed, **{"0": "a"}))
        assert res.out_taint[0] == {"a"}
        assert res.out_taint[1] == frozenset()
        assert any("shard_map" in r.path for r in res.frontier)

        # a collective inside the mapped body propagates like any
        # first-order equation: psum over a tainted shard taints the
        # (replicated) result
        def body2(a, b):
            return b + jax.lax.psum(a, ax)

        f2 = shard_map_nocheck(
            body2, mesh, in_specs=(P(ax), P(ax)), out_specs=P(ax)
        )
        closed2 = jax.make_jaxpr(f2)(jnp.zeros(8), jnp.ones(8))
        res2 = analyze_jaxpr(closed2, _taints(closed2, **{"0": "a"}))
        assert res2.out_taint[0] == {"a"}


class TestNonInterference:
    """The proof over the real engine step/run programs."""

    def test_manifest(self):
        wl = make_raft()
        d = derived_fields(wl)
        assert set(DERIVED_STATE_FIELDS) <= set(d)
        assert set(STORAGE_STATE_FIELDS) <= set(d)  # discipline off
        wl_d = make_raftlog(durable=True)
        assert set(STORAGE_STATE_FIELDS) & set(derived_fields(wl_d)) == set()
        assert set(STORAGE_STATE_FIELDS) <= set(core_fields(wl_d))

    def test_step_all_taps(self):
        rep = check_noninterference(
            make_raft(record=True), CFG, metrics=True, timeline_cap=8,
            cov_words=8, cov_hitcount=True,
        )
        assert rep.ok, rep.summary()
        # the derived columns themselves are legitimately tainted
        # (read-modify-write) and the frontier is non-empty
        assert "met" in rep.out_taint and "cov" in rep.out_taint
        assert rep.frontier
        # report cites SimState field names (the obs.explain vocabulary)
        assert set(rep.derived) == set(derived_fields(make_raft()))

    def test_run_entry_scan_path(self):
        rep = check_noninterference(
            make_raft(record=True), CFG, entry="run", metrics=True,
            cov_words=8, n_steps=3,
        )
        assert rep.ok, rep.summary()
        assert any(
            "scan" in r["path"] or "body" in r["path"] for r in rep.frontier
        )

    def test_sharded_run_entry(self):
        """entry="sharded_run" proves the multi-chip campaign program
        (explore.run_device's simulate stage) THROUGH the shard_map
        boundary — with the campaign tap set on."""
        from madsim_tpu.lint import CAMPAIGN_AXES

        flags = dict(CAMPAIGN_AXES["sharded-campaign"])
        rep = check_noninterference(
            make_raft(record=True), CFG, entry="sharded_run",
            n_seeds=4, n_steps=3, **flags,
        )
        assert rep.ok, rep.summary()
        assert rep.flags["mesh_devices"] == jax.device_count()
        assert "cov" in rep.out_taint and "met" in rep.out_taint
        # the proof walked INTO the mapped body, not around it
        assert any("shard_map" in r["path"] for r in rep.frontier)

    def test_check_axes_device_verification_row(self):
        """check=True traces the check.device detector kernels WITH the
        sim through shard_map (the history-hunt program shape): taint
        set unchanged, the verdict output carries ONLY history taint,
        no callback prims, and step entries are rejected."""
        from madsim_tpu.lint import CHECK_AXES

        flags = dict(CHECK_AXES["device-check"])
        rep = check_noninterference(
            make_raft(record=True), CFG, entry="sharded_run",
            n_seeds=4, n_steps=3, **flags,
        )
        assert rep.ok, rep.summary()
        assert rep.flags["check"] is True
        # the verdict is tainted by the history columns and nothing else
        assert set(rep.out_taint["check_ok"]) <= {
            "hist_word", "hist_t", "hist_count", "hist_drop"
        }
        assert not rep.callback_prims
        with pytest.raises(ValueError, match="entry"):
            check_noninterference(
                make_raft(record=True), CFG, entry="step", check=True,
            )

    def test_sharded_run_planted_leak_is_caught(self):
        # the positive control crosses the call boundary: met comes out
        # of the shard_map'd run and leaks into the RNG cursor — the
        # labels must survive the crossing for the walker to see it
        # (plant_met_leak is step-entry-only, so plant the batched form)
        import dataclasses

        from madsim_tpu.engine.core import MET_SENT

        def batched_met_leak(run_fn):
            def mutant(st):
                out = run_fn(st)
                poison = (out.met[:, MET_SENT] * jnp.int32(0)).astype(
                    jnp.uint32
                )
                return dataclasses.replace(out, step=out.step + poison)

            return mutant

        rep = check_noninterference(
            make_raft(record=True), CFG, entry="sharded_run",
            n_seeds=4, n_steps=3, metrics=True, mutate=batched_met_leak,
        )
        assert not rep.ok
        assert "met" in rep.leaks["step"]["labels"]

    @pytest.mark.slow
    def test_sharded_campaign_matrix(self):
        # the pod-scale acceptance row: every recorded model under the
        # campaign tap set, proved through the shard_map boundary —
        # tools/lint_soak.py runs the same sweep for the artifact
        from madsim_tpu.lint import CAMPAIGN_AXES, check_matrix

        reports = check_matrix(axes=CAMPAIGN_AXES, entry="sharded_run")
        assert len(reports) == len(model_matrix())
        bad = [r.summary() for r in reports if not r.ok]
        assert not bad, "\n".join(bad)

    def test_durable_discipline_reclassifies(self):
        rep = check_noninterference(
            make_raftlog(durable=True, record=True), CFG_RL,
            metrics=True, timeline_cap=8, cov_words=8,
        )
        assert rep.ok, rep.summary()
        assert "disk" not in rep.derived

    def test_planted_met_leak_is_caught(self):
        rep = check_noninterference(
            make_raft(record=True), CFG, metrics=True,
            mutate=plant_met_leak,
        )
        assert not rep.ok
        # the RNG cursor is the leaked core column, met among sources
        assert "step" in rep.leaks
        assert "met" in rep.leaks["step"]["labels"]
        # the offending equation chain is reported, ending in the add
        chain = rep.leaks["step"]["chain"]
        assert chain and chain[-1]["prim"] == "add"
        assert "met" in chain[-1]["sources"]
        assert "reaches core column 'step'" in rep.summary()

    def test_report_is_machine_readable(self):
        rep = check_noninterference(make_raft(), CFG, metrics=True)
        d = rep.to_dict()
        assert d["ok"] and isinstance(d["frontier"], list)
        assert all(
            {"path", "prim", "sources", "mixes_clean"} <= set(r)
            for r in d["frontier"]
        )
        rep.to_json()  # must serialize

    def test_latency_columns_isolated_dense_and_time32(self):
        """The new lowering axes of the matrix (dense one-hot writes,
        int32 pool times) and the latency-marker path: the army
        model's lat_* columns (and the emit-time sidecar) prove
        isolated in the exact programs a TPU runs. The full sweep is
        the slow matrix / make lint --jaxpr."""
        from madsim_tpu.engine import LatencySpec
        from madsim_tpu.models.kvchaos import make_kvchaos

        wl = make_kvchaos(army=True)
        spec = LatencySpec(ops=8, phases=2)
        for layout, t32 in (("dense", False), ("dense", True)):
            rep = check_noninterference(
                wl, CFG, layout=layout, time32=t32, latency=spec,
                timeline_cap=8, cov_words=8,
            )
            assert rep.ok, rep.summary()
            assert "lat_hist" in rep.out_taint
            rep.to_json()  # LatencySpec flags stay JSON-able

    def test_cold_bank_isolated_under_rank_placement(self):
        """The PR-8 placement axis: the cold-bank columns (history,
        timeline, coverage, latency) prove derived-state-isolated in
        BOTH scatter-layout pool-write lowerings — the rank
        select-chain program (the new small-pool CPU default, whose
        select chains the cold-bank appends ride) and the historical
        scatter stores. The full sweep is the slow matrix."""
        from madsim_tpu.engine import LatencySpec
        from madsim_tpu.models.raftlog import make_raftlog

        wl = make_raftlog(army=True)
        spec = LatencySpec(ops=8, phases=2)
        for place in ("rank", "scatter"):
            rep = check_noninterference(
                wl, CFG, layout="scatter", placement=place, latency=spec,
                timeline_cap=8, cov_words=8, metrics=True,
            )
            assert rep.ok, rep.summary()
            assert rep.flags["placement"] == place
            for col in ("hist_word", "tl_t", "cov", "lat_hist", "met"):
                assert col in rep.out_taint, (place, col)

    def test_layout_axes_sweep_and_time32_skip(self):
        from madsim_tpu.lint import check_matrix
        from madsim_tpu.lint.noninterference import LAYOUT_AXES

        assert ("dense", False, None) in LAYOUT_AXES
        assert ("scatter", True, "rank") in LAYOUT_AXES
        # the combined pair is the exact program an accelerator runs
        assert ("dense", True, None) in LAYOUT_AXES
        # BOTH scatter-layout pool-write lowerings (PR 8): the rank
        # select-chain program and the historical .at[].set stores
        assert ("scatter", False, "rank") in LAYOUT_AXES
        assert ("scatter", False, "scatter") in LAYOUT_AXES
        # a non-eligible (workload, config) is skipped for time32
        # pairs instead of failing the matrix
        wl = make_raft()
        wl = type(wl)(**{
            **{f.name: getattr(wl, f.name) for f in
               __import__("dataclasses").fields(wl)},
            "delay_bound_ns": None,
        })
        reps = check_matrix(
            [("raft/unbounded", wl, CFG)], {"base": {}},
            layouts=(("scatter", True),),
        )
        assert reps == []

    @pytest.mark.slow
    def test_full_matrix(self):
        # the acceptance sweep: four recorded models (plus the durable
        # variant) x every build axis — tools/lint_soak.py runs the
        # same matrix for the evidence artifact
        from madsim_tpu.lint import check_matrix

        reports = check_matrix()
        assert len(reports) >= 9 * 6
        bad = [r.summary() for r in reports if not r.ok]
        assert not bad, "\n".join(bad)


SIM = dict(sim_code=True)


class TestLintRules:
    """Each rule has (at least) one negative fixture it catches."""

    def _rules(self, src, **kw):
        return [f.rule for f in lint_source(src, "fx.py", **kw).findings]

    def test_wall_clock(self):
        assert "wall-clock" in self._rules(
            "import time\nseed = int(time.time_ns())\n"
        )
        assert "wall-clock" in self._rules(
            "from datetime import datetime\nx = datetime.now()\n"
        )
        assert "wall-clock" in self._rules(
            "import time as t\nx = t.perf_counter()\n"
        )

    def test_ambient_entropy(self):
        assert "ambient-entropy" in self._rules(
            "import os\nx = os.urandom(8)\n"
        )
        assert "ambient-entropy" in self._rules(
            "import secrets\nx = secrets.token_bytes(4)\n"
        )

    def test_submodule_import_keeps_root_rules_live(self):
        # `import os.path` binds the local name `os` to the ROOT
        # module; the alias map must not remap it to os.path and
        # silently disable the entropy/clock rules on that root
        assert "ambient-entropy" in self._rules(
            "import os.path\nx = os.urandom(8)\n"
        )
        assert "wall-clock" in self._rules(
            "import xml.etree\nimport time\nt = time.time()\n"
        )

    def test_unparseable_file_reports_parse_error_rule(self):
        assert self._rules("def f(:\n") == ["parse-error"]

    def test_uuid(self):
        assert "uuid-entropy" in self._rules("import uuid\nu = uuid.uuid4()\n")
        assert "uuid-entropy" not in self._rules(
            "import uuid\nu = uuid.uuid5(uuid.NAMESPACE_DNS, 'x')\n"
        )

    def test_np_random(self):
        assert "np-random" in self._rules(
            "import numpy as np\nx = np.random.rand(3)\n"
        )
        # an explicitly seeded generator is a deterministic construction
        assert "np-random" not in self._rules(
            "import numpy as np\ng = np.random.default_rng(7)\n"
        )
        assert "np-random" in self._rules(
            "import numpy as np\ng = np.random.default_rng()\n"
        )

    def test_unordered_iter(self):
        assert "unordered-iter" in self._rules(
            "for x in set([1, 2]):\n    pass\n"
        )
        assert "unordered-iter" in self._rules("xs = list({1, 2} | {3})\n")
        assert "unordered-iter" in self._rules(
            "xs = [y for y in frozenset((1, 2))]\n"
        )
        # sorted() launders the order; dict is insertion-ordered
        assert "unordered-iter" not in self._rules(
            "xs = sorted(set([3, 1]))\n"
        )
        assert "unordered-iter" not in self._rules(
            "for k in {'a': 1}:\n    pass\n"
        )

    def test_id_hash_branch(self):
        assert "id-hash-branch" in self._rules(
            "def f(a, b):\n    if id(a) < id(b):\n        return a\n"
        )
        assert "id-hash-branch" in self._rules(
            "x = 1 if hash('k') % 2 else 2\n"
        )
        # id() outside a branch condition is not flagged
        assert "id-hash-branch" not in self._rules("k = id(object())\n")

    def test_fixed_key_scoped_to_sim_code(self):
        src = "import jax\nk = jax.random.PRNGKey(0)\n"
        hits = [
            f.rule for f in lint_source(src, "m.py", **SIM).findings
        ]
        assert "fixed-key" in hits
        # a derived (non-constant) key is the sanctioned construction
        ok = "import jax\nk = jax.random.PRNGKey(seed)\n"
        assert not lint_source(ok, "m.py", **SIM).findings
        # host-side tools may seed however they like
        assert not lint_source(src, "t.py", sim_code=False).findings
        # the alias + jax.random.key spelling resolves too
        src2 = "from jax import random as jr\nk = jr.key(42)\n"
        assert "fixed-key" in [
            f.rule for f in lint_source(src2, "m.py", **SIM).findings
        ]
        # pragma allowlists an intentional fixed key
        src3 = (
            "import jax\n"
            "k = jax.random.PRNGKey(0)  # lint: allow(fixed-key)\n"
        )
        res = lint_source(src3, "m.py", **SIM)
        assert not res.findings and res.allowed

    def test_host_callback_scoped_to_sim_code(self):
        src = (
            "from jax.experimental import io_callback\n"
            "def f(x):\n    return io_callback(print, None, x)\n"
        )
        assert "host-callback" in self._rules(src, sim_code=True)
        assert "host-callback" not in self._rules(src, sim_code=False)
        assert "host-callback" in self._rules(
            "import jax\njax.debug.print('{}', 1)\n", sim_code=True
        )

    def test_pragma_same_line_and_above(self):
        src = (
            "import time\n"
            "t0 = time.monotonic()  # lint: allow(wall-clock)\n"
            "# lint: allow(wall-clock)\n"
            "t1 = time.monotonic()\n"
        )
        res = lint_source(src, "fx.py")
        assert not res.findings
        assert len(res.allowed) == 2

    def test_unused_pragma_is_a_finding(self):
        res = lint_source("x = 1  # lint: allow(np-random)\n", "fx.py")
        assert [f.rule for f in res.findings] == ["unused-allow"]

    def test_dead_pragma_next_to_live_same_rule_pragma(self):
        # usage is tracked per PRAGMA, not per line: a dead pragma is
        # stale even when the adjacent line legitimately uses the same
        # rule (the drift mode where a timer call is deleted but its
        # annotation survives)
        src = (
            "import time\n"
            "t0 = time.monotonic()  # lint: allow(wall-clock)\n"
            "x = 1  # lint: allow(wall-clock)\n"
        )
        res = lint_source(src, "fx.py")
        assert [f.rule for f in res.findings] == ["unused-allow"]
        assert res.findings[0].line == 3
        assert len(res.allowed) == 1

    def test_pragma_must_name_the_right_rule(self):
        src = "import os\nx = os.urandom(4)  # lint: allow(wall-clock)\n"
        rules = self._rules(src)
        assert "ambient-entropy" in rules and "unused-allow" in rules


class TestRepoClean:
    def test_repo_lints_clean(self):
        # the acceptance gate: the whole default surface is finding-free
        # and every intentional site is enumerated by a live pragma
        res = lint_repo()
        assert res.n_files > 50
        msgs = "\n".join(str(f) for f in res.findings)
        assert res.ok, f"repo lint found:\n{msgs}"
        assert len(res.allowed) > 0  # the checked allowlist is non-empty

    def test_matrix_names_six_recorded_models(self):
        names = {n.split("/")[0] for n, _wl, _cfg in model_matrix()}
        assert names == {"raft", "kvchaos", "paxos", "raftlog",
                         "leasekv", "shardkv"}


class TestSyncEio:
    """The observable fsync-EIO window (EmitBuilder errno surface)."""

    def _probe(self):
        # node 0 ticks every 50 ms, writing durable col 0 and syncing;
        # col 1 counts the ticks that observed ctx.sync_err. An EIO
        # window opens at t=0 and closes at 120 ms.
        def on_init(ctx):
            eb = ctx.emits()
            eb.sync_eio(0, when=ctx.now == 0)
            eb.after(50_000_000, user_kind(1), 0, when=ctx.node == 0)
            eb.after(
                120_000_000, KIND_SYNC_OK, 0, (0,), when=ctx.node == 0
            )
            return ctx.state, eb.build()

        def on_tick(ctx):
            eb = ctx.emits()
            new = ctx.state.at[0].set(ctx.state[0] + 1)
            new = new.at[1].set(
                new[1] + ctx.sync_err.astype(jnp.int32)
            )
            eb.sync()
            eb.after(50_000_000, user_kind(1), 0, when=ctx.state[0] < 3)
            eb.halt(when=ctx.state[0] >= 3)
            return new, eb.build()

        return Workload(
            name="eioprobe", n_nodes=1, state_width=2,
            handlers=(on_init, on_tick), max_emits=4,
            durable_cols=(0,), durable_sync=True,
            delay_bound_ns=200_000_000,
        )

    def test_handler_observes_eio_and_syncs_fail(self):
        wl = self._probe()
        cfg = EngineConfig(pool_size=8)
        init = make_init(wl, cfg, metrics=True)
        run = jax.jit(make_run_while(wl, cfg, 64, metrics=True))
        out = run(init(np.zeros(2, np.uint64)))
        st = np.asarray(out.node_state)[0, 0]
        met = np.asarray(out.met)[0]
        # ticks at 50/100 ms fall inside the [0, 120) ms window: both
        # observe sync_err and both syncs fail; later ticks commit
        assert int(st[1]) == 2
        assert int(met[MET_SYNC_LOST]) == 2
        assert int(met[MET_SYNC]) >= 1
        # the last committed sync carried the full counter to disk
        assert int(np.asarray(out.disk)[0, 0, 0]) == int(st[0])
        # both seeds identical (the window is plan-shaped, not drawn)
        assert int(np.asarray(out.node_state)[1, 0, 1]) == 2

    def test_diskfault_eio_windows_compile(self):
        from madsim_tpu.chaos import DiskFault
        from madsim_tpu.engine import KIND_SYNC_LOSS

        spec = DiskFault(targets=(0, 1), n_torn=1, n_sync_loss=1, n_eio=2)
        assert spec.slots == 8
        time, kinds, args, _valid, _node = spec.compile_batch(
            np.arange(4, dtype=np.uint64), slot=0
        )
        on = np.asarray(kinds) == KIND_SYNC_LOSS
        # per seed: one lie window (a1=0) and two EIO windows (a1=1)
        assert (on.sum(axis=1) == 3).all()
        eio_on = (np.asarray(args)[..., 1] == 1) & on
        assert eio_on.sum(axis=1).tolist() == [2] * 4
        # growing n_eio appended AFTER the existing windows: the lie
        # window's draws are unchanged (the spec-offset rule)
        base = DiskFault(targets=(0, 1), n_torn=1, n_sync_loss=1)
        time0, *_rest = base.compile_batch(
            np.arange(4, dtype=np.uint64), slot=0
        )
        np.testing.assert_array_equal(np.asarray(time)[:, :4], time0)

    @pytest.mark.slow
    def test_raftlog_survives_eio_storm(self):
        from madsim_tpu.chaos import CrashStorm, DiskFault, FaultPlan
        from madsim_tpu.check import election_safety, recovery_safety
        from madsim_tpu.engine import search_seeds
        from madsim_tpu.models.raftlog import (
            OP_COMMIT, OP_ELECT, OP_RECOVER, OP_SYNCED,
        )

        cfg = EngineConfig(
            pool_size=128, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
        )
        wl = make_raftlog(record=True, chaos=False, durable=True)
        plan = FaultPlan((
            CrashStorm(
                targets=(0, 1, 2, 3, 4), n=2, t_min_ns=150_000_000,
                t_max_ns=500_000_000, down_min_ns=100_000_000,
                down_max_ns=400_000_000,
            ),
            DiskFault(
                targets=(0, 1, 2, 3, 4), n_torn=0, n_sync_loss=0,
                n_eio=3, t_min_ns=10_000_000, t_max_ns=400_000_000,
                dur_min_ns=100_000_000, dur_max_ns=400_000_000,
            ),
        ), name="eio-storm")

        def inv(h):
            return (
                election_safety(h, elect_op=OP_COMMIT)
                & election_safety(h, elect_op=OP_ELECT)
                & recovery_safety(h, sync_op=OP_SYNCED, recover_op=OP_RECOVER)
            )

        rep = search_seeds(
            wl, cfg, None, history_invariant=inv, plan=plan,
            n_seeds=512, max_steps=4000, metrics=True, require_halt=False,
        )
        assert int((~rep.ok).sum()) == 0
        assert int(rep.overflowed.sum()) == 0
        # the windows were genuinely exercised: observable sync
        # failures happened on most seeds
        assert int((rep.met[:, MET_SYNC_LOST] > 0).sum()) > 256
