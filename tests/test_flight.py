"""Campaign flight recorder (obs/flight.py + obs/prof.py) — the
telemetry schema, the profiler, and the generation-program cache.

Pins, per the round's contract: every generation record carries the
full wall-split keys (compile split OUT of dispatch on both drivers);
heartbeats are monotone and interleave with generation records; the
campaign Perfetto export has exactly one generation span per
generation and monotone counter tracks; the profiler retrace counter
pins (same cache key across campaigns -> no retrace; changed space ->
exactly one); and the flight-recorder on/off bit-identity across both
drivers. Soak-scale certificates live in tools/flight_soak.py
(FLIGHT_r08.txt)."""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from madsim_tpu import explore, obs
from madsim_tpu.chaos import FaultPlan, GrayFailure, PauseStorm
from madsim_tpu.engine import EngineConfig, search_seeds
from madsim_tpu.explore import device as _device
from madsim_tpu.models import make_raft
from madsim_tpu.obs import prof

NODES = (0, 1, 2, 3, 4)
CFG = EngineConfig(pool_size=64, loss_p=0.02)
PLAN = FaultPlan((
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="flight-test")


def _halt_inv(view):
    return view["halted"]


# ONE workload + invariant object across the module: program caches key
# on identity (the engine.search rule), which is also what the
# multi-campaign retrace pin needs
WL = make_raft()
KW = dict(generations=3, batch=16, root_seed=11, max_steps=200,
          cov_words=8, invariant=_halt_inv)

DEVICE_WALL_KEYS = ("dispatch_wall_s", "compile_wall_s", "sync_wall_s",
                    "queue_wall_s", "idle_wall_s")
HOST_WALL_KEYS = ("dispatch_wall_s", "compile_wall_s", "mutate_wall_s",
                  "admit_wall_s", "host_wall_s",
                  "queue_wall_s", "idle_wall_s")


def _fp(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


# lazily computed shared results (tier-1 wall is a budgeted resource):
# the baseline device/host campaigns with no telemetry, and one flight-
# recorded device campaign (records captured in-memory)
_SHARED: dict = {}


def _rep_off(driver):
    key = f"off-{driver}"
    if key not in _SHARED:
        runner = explore.run_device if driver == "device" else explore.run
        _SHARED[key] = runner(WL, CFG, PLAN, **KW)
    return _SHARED[key]


def _flight_records():
    """One flight-recorded device campaign from a COLD program cache
    (so compile events are present), records captured in-memory."""
    if "records" not in _SHARED:
        _device._GEN_CACHE.clear()
        records = []
        with obs.FlightRecorder(records.append, heartbeat_s=0.0) as fr:
            _SHARED["rep-flight"] = explore.run_device(
                WL, CFG, PLAN, telemetry=fr, **KW
            )
        _SHARED["records"] = records
    return _SHARED["records"]


# ---------------------------------------------------------------------------
# obs.prof units
# ---------------------------------------------------------------------------


def test_aot_program_build_and_retrace_counting():
    p = prof.AotProgram("t.unit", ("k", 1), lambda x: (x * 2).sum())
    with prof.profiled() as session:
        out = p(jnp.ones((8, 8)))
        assert float(out) == 128.0
        assert p.builds == 1 and p.last_build_s > 0
        out2 = p(jnp.ones((8, 8)))  # warm: same signature
        assert float(out2) == 128.0
        assert p.builds == 1 and p.last_build_s == 0.0
        p(jnp.ones((4, 4)))  # new signature -> counted retrace
        assert p.builds == 2
        rec = session.programs[("t.unit", p.key)]
        assert rec.traces == 2 and rec.calls == 3
        assert rec.compile_wall_s > 0 and rec.execute_wall_s > 0
        # cost analysis + memory footprint landed at build time
        assert rec.flops > 0 and rec.arg_bytes > 0
        events = session.pop_events()
        assert len(events) == 2 and events[0]["program"] == "t.unit"
        assert session.pop_events() == []
        assert "t.unit" in session.report()
    assert prof.current() is None  # profiled() restored the state


def test_aot_program_matches_jit_bit_exact():
    fn = lambda x: jnp.sin(x).sum()  # noqa: E731
    x = jnp.linspace(0.0, 5.0, 257)
    aot = prof.AotProgram("t.bit", "k", fn)(x)
    assert np.asarray(aot) == np.asarray(jax.jit(fn)(x))


def test_device_memory_accounting():
    keep = jnp.arange(1024, dtype=jnp.int32)  # a buffer we know is live
    mem = prof.device_memory()
    assert mem["live_buffers"] >= 1
    assert mem["live_buffer_bytes"] >= keep.nbytes


def test_search_report_build_wall_split():
    wl = make_raft()  # fresh identity: guaranteed cold run cache
    inv = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731
    r1 = search_seeds(wl, CFG, inv, n_seeds=8, max_steps=64)
    r2 = search_seeds(wl, CFG, inv, n_seeds=8, max_steps=64)
    assert r1.build_wall_s > 0.0  # cold: trace+lower+compile measured
    assert r2.build_wall_s == 0.0  # warm: pure execution
    assert np.array_equal(r1.traces, r2.traces)


# ---------------------------------------------------------------------------
# the generation-program cache (the tentpole pin)
# ---------------------------------------------------------------------------


def test_retraces_once_across_three_campaigns():
    _device._GEN_CACHE.clear()
    with prof.profiled() as p:
        reps = [
            explore.run_device(WL, CFG, PLAN, **{**KW, "root_seed": rs})
            for rs in (11, 12, 13)
        ]
    retr = p.retraces("explore.device")
    # one uniform + one breed program, each traced EXACTLY once for the
    # whole session (was: one full rebuild per campaign)
    assert sorted(k[0] for k in retr) == [
        "explore.device.breed", "explore.device.uniform",
    ]
    assert all(v == 1 for v in retr.values())
    assert reps[0].wall_compile_s > 0.0
    assert reps[1].wall_compile_s == 0.0
    assert reps[2].wall_compile_s == 0.0
    # root seed is a runtime argument, not a baked constant: different
    # roots through one program still diverge
    assert _fp(reps[0]) != _fp(reps[1])
    _SHARED["off-device"] = reps[0]  # root 11 == KW's campaign


def test_changed_space_retraces_exactly_once():
    plan2 = FaultPlan((
        PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
                   t_max_ns=300_000_000, down_min_ns=50_000_000,
                   down_max_ns=200_000_000),
    ), name="flight-test-2")
    explore.run_device(WL, CFG, PLAN, **KW)  # warm the original key
    with prof.profiled() as p:
        explore.run_device(WL, CFG, PLAN, **KW)  # cache hit: no build
        explore.run_device(
            WL, CFG, plan2, **{**KW, "generations": 1}
        )  # new space hash -> exactly one uniform build
    retr = p.retraces("explore.device")
    # the cache-hit campaign executed through existing programs
    # (records with traces == 0); only the new space hash built — and
    # exactly once, its uniform program (generations=1 never breeds)
    assert sum(retr.values()) == 1
    built = [k[0] for k, v in retr.items() if v > 0]
    assert built == ["explore.device.uniform"]


def test_flight_on_off_bit_identity_device():
    _flight_records()  # the flight-recorded campaign (profiler armed)
    assert _fp(_rep_off("device")) == _fp(_SHARED["rep-flight"])


def test_flight_on_off_bit_identity_host(tmp_path):
    off = _rep_off("host")
    path = tmp_path / "host.jsonl"
    with obs.FlightRecorder(str(path), heartbeat_s=0.0) as fr:
        on = explore.run(WL, CFG, PLAN, telemetry=fr, **KW)
    assert _fp(off) == _fp(on)
    _SHARED["host-jsonl"] = [
        json.loads(line) for line in path.read_text().splitlines()
    ]


# ---------------------------------------------------------------------------
# telemetry schema + heartbeats
# ---------------------------------------------------------------------------


def test_device_generation_records_carry_wall_split():
    recs = _flight_records()
    gens = [r for r in recs if r["event"] == "generation"]
    assert len(gens) == KW["generations"]
    for g in gens:
        for k in DEVICE_WALL_KEYS:
            assert k in g, f"missing {k}"
        assert g["host_syncs"] == 1
        # the pipeline split exists on BOTH drivers; blocking emits 0s
        assert g["queue_wall_s"] == 0.0 and g["idle_wall_s"] == 0.0
    # the cold generation paid the build; warm generations are
    # compile-free — the split the old accounting hid inside dispatch
    assert gens[0]["compile_wall_s"] > 0
    assert gens[-1]["compile_wall_s"] == 0.0
    end = next(r for r in recs if r["event"] == "campaign_end")
    assert {"wall_dispatch_s", "wall_compile_s", "wall_sync_s",
            "wall_queue_s", "wall_idle_s"} <= set(end)
    assert end["wall_queue_s"] == 0.0 and end["wall_idle_s"] == 0.0


def test_host_generation_records_carry_wall_split(tmp_path):
    if "host-jsonl" not in _SHARED:
        test_flight_on_off_bit_identity_host(tmp_path)
    gens = [
        r for r in _SHARED["host-jsonl"] if r["event"] == "generation"
    ]
    assert len(gens) == KW["generations"]
    for g in gens:
        for k in HOST_WALL_KEYS:
            assert k in g, f"missing {k}"


def test_heartbeats_monotone_and_interleaved():
    recs = _flight_records()
    assert [r["seq"] for r in recs] == list(range(len(recs)))
    hbs = [r for r in recs if r["event"] == "heartbeat"]
    assert len(hbs) == KW["generations"]  # heartbeat_s=0: one per gen
    done = [h["generations_done"] for h in hbs]
    assert done == sorted(done) == [1, 2, 3]
    ts = [r["t_s"] for r in recs]
    assert ts == sorted(ts)
    # interleave: each heartbeat lands directly after its generation
    events = [r["event"] for r in recs]
    for i, ev in enumerate(events):
        if ev == "heartbeat":
            assert events[i - 1] == "generation"
    assert hbs[0]["gens_per_s"] > 0
    assert hbs[0]["live_buffer_bytes"] > 0  # the memory tap
    # compile events (profiler builds) precede the generation they
    # delayed, and the summary closes the log
    assert events[-1] == "flight_summary"
    summary = recs[-1]
    names = {p["name"] for p in summary["programs"]}
    assert "explore.device.uniform" in names
    assert "memory" in summary


# ---------------------------------------------------------------------------
# campaign Perfetto
# ---------------------------------------------------------------------------


def test_campaign_perfetto_spans_and_counters(tmp_path):
    recs = _flight_records()
    doc = obs.campaign_perfetto(recs)
    spans = [
        e for e in doc["traceEvents"] if e.get("cat") == "generation"
    ]
    assert len(spans) == KW["generations"]  # span count == generations
    assert doc["otherData"]["generations"] == KW["generations"]
    for name in ("cov_bits", "violations", "corpus_size"):
        track = [
            e["args"][name] for e in doc["traceEvents"]
            if e.get("ph") == "C" and e.get("name") == name
        ]
        assert len(track) == KW["generations"]
        assert track == sorted(track), f"{name} track not monotone"
    assert any(e.get("cat") == "compile" for e in doc["traceEvents"])
    assert any(e.get("name") == "live_buffer_bytes"
               for e in doc["traceEvents"])
    # sub-spans stay inside their generation span
    phases = [e for e in doc["traceEvents"] if e.get("cat") == "phase"]
    assert phases
    for ph in phases:
        parent = next(
            s for s in spans
            if s["ts"] - 1 <= ph["ts"]
            and ph["ts"] + ph["dur"] <= s["ts"] + s["dur"] + 1
        )
        assert parent is not None
    # the file path form (incl. a torn last line) reads identically
    path = tmp_path / "c.jsonl"
    with open(path, "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")
        fh.write('{"event": "generation", "torn')  # crashed mid-write
    doc2 = obs.campaign_perfetto(str(path))
    assert doc2["otherData"]["generations"] == KW["generations"]


def test_jsonl_sink_flushes_and_fsyncs(tmp_path):
    path = tmp_path / "s.jsonl"
    sink = obs.JsonlSink(str(path), fsync=True)
    sink({"event": "generation", "generation": 0})
    # readable BEFORE close: per-record flush is the crash contract
    assert json.loads(path.read_text())["generation"] == 0
    sink({"event": "campaign_end"})
    assert len(path.read_text().splitlines()) == 2
    sink.close()


# ---------------------------------------------------------------------------
# the flight boundary (lint matrix entry)
# ---------------------------------------------------------------------------


def test_flight_taps_never_enter_traced_code():
    from madsim_tpu.lint.noninterference import (
        FLIGHT_AXES,
        check_noninterference,
    )

    flags = dict(FLIGHT_AXES["flight-campaign"])
    assert flags.pop("flight") is True
    base = check_noninterference(WL, CFG, entry="run", **flags)
    armed = check_noninterference(
        WL, CFG, entry="run", flight=True, **flags
    )
    assert base.ok and armed.ok
    assert armed.callback_prims == []
    assert armed.flags["flight"] is True
    # profiler active vs not: the traced program is THE SAME program
    assert armed.n_eqns == base.n_eqns


# ---------------------------------------------------------------------------
# campaign_top
# ---------------------------------------------------------------------------


def test_campaign_top_renders_live_and_finished():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    try:
        import campaign_top
    finally:
        sys.path.pop(0)
    recs = _flight_records()
    frame = campaign_top.render(recs, "x.jsonl")
    assert "raft" in frame and "3/3 generations" in frame
    assert "coverage" in frame and "violations" in frame
    assert "compile" in frame  # the wall split made it to the screen
    assert "programs (flight summary):" in frame
    # a live (mid-campaign, no end record) log still renders
    live = [r for r in recs if r["event"] not in
            ("campaign_end", "flight_summary")][:3]
    frame2 = campaign_top.render(live)
    assert "running" in frame2
    # and the file reader tolerates a torn tail
    assert campaign_top.read_records("/nonexistent.jsonl") == []
