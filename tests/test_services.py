"""Service-simulator integration tests.

Mirrors the reference's integration suites (SURVEY.md §4):
  * tonic-example/src/server.rs:129-406 — unary + streaming RPC shapes,
    invalid address, client_crash (random-time client restarts),
    client-drops-stream, server_crash => UNAVAILABLE
  * madsim-etcd-client tests — kv/txn/lease/election semantics + fault
    injection
  * madsim-rdkafka/tests/test.rs:20-169 — multi-node producers/consumers
    exactly-once sum check
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.services import etcd, grpc, kafka


def run(seed, coro_fn, config=None, time_limit=120.0):
    rt = ms.Runtime(seed=seed, config=config)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


# ---------------------------------------------------------------------------
# gRPC-style services (tonic parity)
# ---------------------------------------------------------------------------


class Greeter:
    """The tonic-example service shape (4 RPC kinds)."""

    SERVICE_NAME = "helloworld.Greeter"

    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(5):
            await ms.sleep(0.01)
            yield {"message": f"{request.message['name']}#{i}"}

    async def record_hellos(self, stream):
        names = []
        async for msg in stream:
            names.append(msg["name"])
        return {"message": f"Hello {', '.join(names)}!"}

    async def chat(self, stream):
        async for msg in stream:
            yield {"message": f"ack:{msg['name']}"}


def _spawn_greeter(h, ip="10.0.0.1", port=50051):
    async def serve():
        await grpc.Server.builder().add_service(Greeter()).serve(f"0.0.0.0:{port}")

    node = h.create_node().name("grpc-server").ip(ip).init(serve).build()
    return node, f"{ip}:{port}"


def test_grpc_unary():
    async def main():
        h = ms.Handle.current()
        _, addr = _spawn_greeter(h)
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            r = await c.say_hello({"name": "world"})
            assert r == {"message": "Hello world!"}
            return True

        return await cli.spawn(client())

    assert run(1, main)


def test_grpc_server_streaming():
    async def main():
        h = ms.Handle.current()
        _, addr = _spawn_greeter(h)
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            stream = await c.lots_of_replies({"name": "x"})
            msgs = [m async for m in stream]
            assert [m["message"] for m in msgs] == [f"x#{i}" for i in range(5)]
            return True

        return await cli.spawn(client())

    assert run(2, main)


def test_grpc_client_streaming_and_bidi():
    async def main():
        h = ms.Handle.current()
        _, addr = _spawn_greeter(h)
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            tx, reply = await c.record_hellos()
            for n in ("a", "b", "c"):
                await tx.send({"name": n})
            await tx.finish()
            r = await reply
            assert r == {"message": "Hello a, b, c!"}

            tx, stream = await c.chat()
            await tx.send({"name": "1"})
            assert (await stream.message())["message"] == "ack:1"
            await tx.send({"name": "2"})
            assert (await stream.message())["message"] == "ack:2"
            await tx.finish()
            assert await stream.message() is None
            return True

        return await cli.spawn(client())

    assert run(3, main)


def test_grpc_invalid_address_unavailable():
    """Connecting to an unbound address fails fast with UNAVAILABLE
    (tonic-example invalid-address test)."""

    async def main():
        h = ms.Handle.current()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            with pytest.raises(grpc.Status) as ei:
                await grpc.connect("10.9.9.9:1")
            assert ei.value.code == grpc.Code.UNAVAILABLE
            return True

        return await cli.spawn(client())

    assert run(4, main)


def test_grpc_server_crash_unavailable():
    """Kill the server mid-session: in-flight and subsequent calls fail
    UNAVAILABLE (tonic-example/src/server.rs:371-405)."""

    async def main():
        h = ms.Handle.current()
        server, addr = _spawn_greeter(h)
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            r = await c.say_hello({"name": "a"})
            assert r["message"] == "Hello a!"
            h.kill(server)
            with pytest.raises(grpc.Status) as ei:
                await c.say_hello({"name": "b"})
            assert ei.value.code == grpc.Code.UNAVAILABLE
            return True

        return await cli.spawn(client())

    assert run(5, main)


def test_grpc_client_crash_server_survives():
    """Clients killed at random times mid-call; the server keeps serving
    (tonic-example/src/server.rs:283-331)."""

    async def main():
        h = ms.Handle.current()
        _, addr = _spawn_greeter(h)

        for i in range(10):
            async def client():
                ch = await grpc.connect(addr)
                c = grpc.service_client(Greeter, ch)
                while True:
                    await c.say_hello({"name": "spin"})

            node = h.create_node().name(f"victim{i}").ip(f"10.0.1.{i+1}").build()
            node.spawn(client())
            await ms.sleep(ms.thread_rng().random_float() * 0.5)
            h.kill(node)

        # server must still answer a fresh client
        probe = h.create_node().name("probe").ip("10.0.0.99").build()

        async def check():
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            r = await c.say_hello({"name": "still-alive"})
            return r["message"]

        assert await probe.spawn(check()) == "Hello still-alive!"
        return True

    assert run(6, main)


def test_grpc_client_drops_stream():
    """Client abandons a bidi stream without finishing; the server-side
    handler ends instead of hanging (server.rs:333-369)."""

    async def main():
        h = ms.Handle.current()
        _, addr = _spawn_greeter(h)
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            tx, stream = await c.chat()
            await tx.send({"name": "x"})
            assert (await stream.message())["message"] == "ack:x"
            tx.drop()  # abandon without end-marker
            await ms.sleep(1.0)
            # server still serves new calls afterwards
            r = await c.say_hello({"name": "after"})
            assert r["message"] == "Hello after!"
            return True

        return await cli.spawn(client())

    assert run(7, main)


# ---------------------------------------------------------------------------
# etcd simulator
# ---------------------------------------------------------------------------


def _spawn_etcd(h, timeout_rate=0.0, ip="10.0.2.1", port=2379):
    async def serve():
        await etcd.SimServer(timeout_rate=timeout_rate).serve(f"0.0.0.0:{port}")

    h.create_node().name("etcd").ip(ip).init(serve).build()
    return f"{ip}:{port}"


def test_etcd_kv_and_revisions():
    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        cli = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c = await etcd.Client.connect([addr])
            r1 = await c.put("k1", "v1")
            r2 = await c.put("k1", "v2")
            assert r2["header_revision"] == r1["header_revision"] + 1
            g = await c.get("k1")
            kv = g["kvs"][0]
            assert kv.value == b"v2" and kv.version == 2
            assert kv.create_revision == r1["header_revision"]
            assert kv.mod_revision == r2["header_revision"]
            # prefix range
            await c.put("k2", "x")
            await c.put("other", "y")
            g = await c.get("k", etcd.GetOptions(prefix=True))
            assert [kv.key for kv in g["kvs"]] == [b"k1", b"k2"]
            d = await c.delete("k", etcd.DeleteOptions(prefix=True))
            assert d["deleted"] == 2
            g = await c.get("k", etcd.GetOptions(prefix=True))
            assert g["count"] == 0
            return True

        return await cli.spawn(app())

    assert run(10, main)


def test_etcd_txn():
    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        cli = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c = await etcd.Client.connect([addr])
            await c.put("k", "1")
            t = (
                etcd.Txn()
                .when([etcd.Compare.value("k", "=", "1")])
                .and_then([etcd.TxnOp.put("k", "2")])
                .or_else([etcd.TxnOp.put("k", "bad")])
            )
            r = await c.txn(t)
            assert r["succeeded"]
            assert (await c.get("k"))["kvs"][0].value == b"2"
            # failing compare takes the else branch
            r = await c.txn(t)
            assert not r["succeeded"]
            assert (await c.get("k"))["kvs"][0].value == b"bad"
            return True

        return await cli.spawn(app())

    assert run(11, main)


def test_etcd_lease_expiry_deletes_keys():
    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        cli = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c = await etcd.Client.connect([addr])
            lease = await c.lease_client().grant(ttl=3)
            await c.put("ephemeral", "x", etcd.PutOptions(lease=lease["id"]))
            assert (await c.get("ephemeral"))["count"] == 1
            # keep-alives hold it
            for _ in range(4):
                await ms.sleep(1.0)
                await c.lease_client().keep_alive(lease["id"])
            assert (await c.get("ephemeral"))["count"] == 1
            # stop keep-alive: expires after ttl
            await ms.sleep(5.0)
            assert (await c.get("ephemeral"))["count"] == 0
            with pytest.raises(etcd.EtcdError):
                await c.lease_client().time_to_live(lease["id"])
            return True

        return await cli.spawn(app())

    assert run(12, main)


def test_etcd_election_campaign_resign():
    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        app_node = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c1 = await etcd.Client.connect([addr])
            c2 = await etcd.Client.connect([addr])
            l1 = await c1.lease_client().grant(ttl=60)
            l2 = await c2.lease_client().grant(ttl=60)
            e1 = c1.election_client()
            e2 = c2.election_client()
            win1 = await e1.campaign("mayor", "alice", l1["id"])
            leader = await e2.leader("mayor")
            assert leader["kv"].value == b"alice"
            # second campaign blocks until the first resigns
            second = ms.spawn(e2.campaign("mayor", "bob", l2["id"]))
            await ms.sleep(1.0)
            assert not second.done()
            await e1.proclaim(win1["key"], "alice2")
            assert (await e2.leader("mayor"))["kv"].value == b"alice2"
            await e1.resign(win1["key"])
            win2 = await second
            assert (await e1.leader("mayor"))["kv"].value == b"bob"
            await e2.resign(win2["key"])
            with pytest.raises(etcd.EtcdError, match="no leader"):
                await e1.leader("mayor")
            return True

        return await app_node.spawn(app())

    assert run(13, main)


def test_etcd_election_observe():
    """observe streams campaign -> proclaim -> resign -> handover; the
    reference server answers this op with Unimplemented (server.rs:60)."""

    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        app_node = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c1 = await etcd.Client.connect([addr])
            c2 = await etcd.Client.connect([addr])
            obs_cli = await etcd.Client.connect([addr])
            l1 = await c1.lease_client().grant(ttl=60)
            l2 = await c2.lease_client().grant(ttl=60)
            e1 = c1.election_client()
            e2 = c2.election_client()

            stream = await obs_cli.election_client().observe("mayor")
            seen = []

            async def observer():
                async for resp in stream:
                    seen.append(resp["kv"].value)

            obs_task = ms.spawn(observer())

            win1 = await e1.campaign("mayor", "alice", l1["id"])
            await ms.sleep(0.5)
            await e1.proclaim(win1["key"], "alice2")
            await ms.sleep(0.5)
            second = ms.spawn(e2.campaign("mayor", "bob", l2["id"]))
            await ms.sleep(0.5)
            await e1.resign(win1["key"])
            await second
            await ms.sleep(0.5)
            assert seen == [b"alice", b"alice2", b"bob"]
            stream.close()
            await ms.sleep(0.5)
            assert obs_task.done()
            return True

        return await app_node.spawn(app())

    assert run(15, main)


def test_etcd_election_lease_expiry_hands_over():
    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h)
        app_node = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c1 = await etcd.Client.connect([addr])
            c2 = await etcd.Client.connect([addr])
            l1 = await c1.lease_client().grant(ttl=2)
            l2 = await c2.lease_client().grant(ttl=60)
            await c1.election_client().campaign("boss", "a", l1["id"])
            second = ms.spawn(c2.election_client().campaign("boss", "b", l2["id"]))
            # let l1 expire (no keep-alive): leadership moves
            await second
            assert (await c2.election_client().leader("boss"))["kv"].value == b"b"
            return True

        return await app_node.spawn(app())

    assert run(14, main)


def test_etcd_fault_injection_timeouts():
    """With timeout_rate=1 every request stalls 5-15s and fails
    Unavailable (service.rs:113-124)."""

    async def main():
        h = ms.Handle.current()
        addr = _spawn_etcd(h, timeout_rate=1.0)
        cli = h.create_node().name("app").ip("10.0.2.2").build()

        async def app():
            await ms.sleep(0.1)
            c = await etcd.Client.connect([addr])
            t0 = ms.now_ns()
            with pytest.raises(etcd.EtcdError, match="Unavailable"):
                await c.put("k", "v")
            waited = (ms.now_ns() - t0) / 1e9
            assert waited >= 5.0
            return True

        return await cli.spawn(app())

    assert run(15, main)


# ---------------------------------------------------------------------------
# kafka simulator
# ---------------------------------------------------------------------------


def test_kafka_exactly_once_sum():
    """The reference's rdkafka integration shape (tests/test.rs:20-169):
    broker + admin + 2 producers + 2 consumers; every produced value is
    consumed exactly once."""

    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.3.1").init(serve).build()
        addr = "10.0.3.1:9092"

        admin_node = h.create_node().name("admin").ip("10.0.3.2").build()

        async def mk_admin():
            await ms.sleep(0.1)
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("events", 4)])

        await admin_node.spawn(mk_admin())

        async def producer(base):
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            p = await cfg.create(kafka.FutureProducer)
            for i in range(50):
                await p.send(
                    kafka.BaseRecord.to("events").set_payload(str(base + i))
                )

        p1 = h.create_node().name("p1").ip("10.0.3.3").build()
        p2 = h.create_node().name("p2").ip("10.0.3.4").build()
        j1 = p1.spawn(producer(0))
        j2 = p2.spawn(producer(1000))
        await j1
        await j2

        async def consumer(partitions):
            cfg = (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("auto.offset.reset", "earliest")
            )
            c = await cfg.create(kafka.BaseConsumer)
            tpl = kafka.TopicPartitionList()
            for p in partitions:
                tpl.add_partition("events", p)
            await c.assign(tpl)
            got = []
            idle = 0
            while idle < 20:
                msg = await c.poll()
                if msg is None:
                    idle += 1
                    await ms.sleep(0.05)
                else:
                    idle = 0
                    got.append(int(msg.payload))
            return got

        c1 = h.create_node().name("c1").ip("10.0.3.5").build()
        c2 = h.create_node().name("c2").ip("10.0.3.6").build()
        g1 = await c1.spawn(consumer([0, 1]))
        g2 = await c2.spawn(consumer([2, 3]))
        all_vals = sorted(g1 + g2)
        expect = sorted(list(range(50)) + list(range(1000, 1050)))
        assert all_vals == expect, "every value consumed exactly once"
        return True

    assert run(20, main)


def test_kafka_producer_queue_full_and_round_robin():
    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.3.1").init(serve).build()
        addr = "10.0.3.1:9092"
        app = h.create_node().name("app").ip("10.0.3.2").build()

        async def go():
            await ms.sleep(0.1)
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("t", 3)])
            p = await cfg.create(kafka.BaseProducer)
            for i in range(10):
                p.send(kafka.BaseRecord.to("t").set_payload(str(i)))
            # 11th buffered record: QueueFull (producer.rs:173-190)
            with pytest.raises(kafka.KafkaError, match="QueueFull"):
                p.send(kafka.BaseRecord.to("t").set_payload("x"))
            acks = await p.flush()
            # round-robin across 3 partitions even though none requested
            assert [part for (_t, part, _o) in acks] == [
                0, 1, 2, 0, 1, 2, 0, 1, 2, 0
            ]
            # requested partition is ignored (broker.rs:81-111)
            fp = await cfg.create(kafka.FutureProducer)
            part, off = await fp.send(
                kafka.BaseRecord.to("t").set_partition(2).set_payload("y")
            )
            assert part == 1  # round-robin cursor continues
            return True

        return await app.spawn(go())

    assert run(21, main)


def test_kafka_transactions_and_stream_consumer():
    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.3.1").init(serve).build()
        addr = "10.0.3.1:9092"
        app = h.create_node().name("app").ip("10.0.3.2").build()

        async def go():
            await ms.sleep(0.1)
            cfg = (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("auto.offset.reset", "earliest")
            )
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("t", 1)])

            p = await cfg.create(kafka.BaseProducer)
            await p.init_transactions()
            p.begin_transaction()
            p.send(kafka.BaseRecord.to("t").set_payload("aborted"))
            p.abort_transaction()
            p.begin_transaction()
            p.send(kafka.BaseRecord.to("t").set_payload("committed"))
            await p.commit_transaction()

            c = await cfg.create(kafka.StreamConsumer)
            tpl = kafka.TopicPartitionList()
            tpl.add_partition_offset("t", 0, kafka.Offset("beginning"))
            await c.assign(tpl)
            msg = await c.recv()
            assert msg.payload == b"committed"
            lo, hi = await c.fetch_watermarks("t", 0)
            assert (lo, hi) == (0, 1), "aborted record never reached the log"
            return True

        return await app.spawn(go())

    assert run(22, main)


def test_services_deterministic_across_seeds():
    """Same seed => same interleaving for a grpc+etcd workload."""

    def scenario(seed):
        events = []

        async def main():
            h = ms.Handle.current()
            _, addr = _spawn_greeter(h)
            eaddr = _spawn_etcd(h)
            cli = h.create_node().name("cli").ip("10.0.0.2").build()

            async def go():
                await ms.sleep(0.1)
                ch = await grpc.connect(addr)
                c = grpc.service_client(Greeter, ch)
                ec = await etcd.Client.connect([eaddr])
                for i in range(5):
                    r = await c.say_hello({"name": str(i)})
                    await ec.put(f"k{i}", r["message"])
                    events.append((round(ms.now_ns() / 1e6, 3), r["message"]))
                return True

            return await cli.spawn(go())

        run(seed, main)
        return events

    assert scenario(42) == scenario(42)
    assert scenario(42) != scenario(43)


def test_kafka_consumer_group_splits_partitions():
    """Two consumers in one group: the coordinator range-assigns the
    topic's partitions disjointly and every message is consumed exactly
    once across the group (beats the assign-only reference sim,
    madsim-rdkafka/src/sim/consumer.rs:110-122)."""

    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.4.1").init(serve).build()
        addr = "10.0.4.1:9092"

        setup = h.create_node().name("setup").ip("10.0.4.2").build()

        async def mk():
            await ms.sleep(0.1)
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("jobs", 4)])
            p = await cfg.create(kafka.FutureProducer)
            for i in range(40):
                await p.send(kafka.BaseRecord.to("jobs").set_payload(str(i)))

        await setup.spawn(mk())

        def consumer_cfg():
            return (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("group.id", "workers")
                .set("auto.offset.reset", "earliest")
                .set("session.timeout.ms", "5000")
                .set("heartbeat.interval.ms", "500")
            )

        async def worker(results):
            c = await consumer_cfg().create(kafka.BaseConsumer)
            await c.subscribe(["jobs"])
            idle = 0
            while idle < 20:
                m = await c.poll()
                if m is None:
                    idle += 1
                    await ms.sleep(0.05)
                else:
                    idle = 0
                    results.append((m.partition, int(m.payload)))
            assign = c.assignment()
            await c.close()
            return assign

        n1 = h.create_node().name("c1").ip("10.0.4.3").build()
        n2 = h.create_node().name("c2").ip("10.0.4.4").build()
        r1: list = []
        r2: list = []
        j1 = n1.spawn(worker(r1))
        j2 = n2.spawn(worker(r2))
        a1 = await j1
        a2 = await j2

        # disjoint assignment covering all 4 partitions, 2 each
        assert len(a1) == 2 and len(a2) == 2
        assert not (set(a1) & set(a2))
        assert set(a1) | set(a2) == {("jobs", p) for p in range(4)}
        # exactly-once across the group
        seen = sorted(v for _p, v in r1 + r2)
        assert seen == list(range(40))
        assert not ({p for p, _ in r1} & {p for p, _ in r2})
        return True

    assert run(7, main) is True


def test_kafka_consumer_group_rebalances_on_death():
    """Kill one group member mid-stream: its session times out, the
    coordinator rebalances, and the survivor picks up the dead member's
    partitions from the committed offsets — no message lost."""

    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.5.1").init(serve).build()
        addr = "10.0.5.1:9092"
        setup = h.create_node().name("setup").ip("10.0.5.2").build()

        async def mk():
            await ms.sleep(0.1)
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("jobs", 4)])
            p = await cfg.create(kafka.FutureProducer)
            for i in range(60):
                await p.send(kafka.BaseRecord.to("jobs").set_payload(str(i)))

        await setup.spawn(mk())

        def consumer_cfg():
            return (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("group.id", "workers")
                .set("auto.offset.reset", "earliest")
                .set("session.timeout.ms", "2000")
                .set("heartbeat.interval.ms", "300")
                .set("auto.commit.interval.ms", "200")
            )

        victim_node = h.create_node().name("victim").ip("10.0.5.3").build()
        survivor_node = h.create_node().name("survivor").ip("10.0.5.4").build()

        async def victim():
            c = await consumer_cfg().create(kafka.BaseConsumer)
            await c.subscribe(["jobs"])
            got = 0
            while got < 5:  # consume a few, commit, then get killed
                m = await c.poll()
                if m is not None:
                    got += 1
                await ms.sleep(0.05)
            await c.commit()
            await ms.sleep(1000)  # hang (killed below) without leaving

        async def survivor(results):
            c = await consumer_cfg().create(kafka.BaseConsumer)
            await c.subscribe(["jobs"])
            assert len(c.assignment()) == 2
            idle = 0
            while idle < 40:
                m = await c.poll()
                if m is None:
                    idle += 1
                    await ms.sleep(0.2)
                else:
                    idle = 0
                    results.append(int(m.payload))
            assign = c.assignment()
            await c.close()
            return assign

        victim_node.spawn(victim())
        results: list = []
        j = survivor_node.spawn(survivor(results))
        await ms.sleep(2.0)
        h.kill(victim_node.id)  # no leave_group: only the session reaps it
        final_assign = await j

        # after the rebalance the survivor owns all 4 partitions
        assert set(final_assign) == {("jobs", p) for p in range(4)}
        # nothing is lost: the survivor's own messages plus re-reading
        # from the victim's committed offsets cover every payload the
        # victim did not durably consume
        assert len(set(results)) >= 60 - 5
        return True

    assert run(11, main) is True


def test_kafka_consumer_group_stabilizes():
    """After membership stops changing, the generation must converge:
    a rejoin with unchanged subscriptions does NOT bump the generation
    (otherwise every rejoin invalidates every other member, forever)."""

    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.6.1").init(serve).build()
        addr = "10.0.6.1:9092"
        setup = h.create_node().name("setup").ip("10.0.6.2").build()

        async def mk():
            await ms.sleep(0.1)
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("t", 2)])

        await setup.spawn(mk())

        def ccfg():
            return (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("group.id", "g")
                .set("auto.offset.reset", "earliest")
                .set("heartbeat.interval.ms", "100")
            )

        async def pair(node_ip, results):
            c = await ccfg().create(kafka.BaseConsumer)
            await c.subscribe(["t"])
            # 30 polls x >= heartbeat interval: plenty of heartbeats
            for _ in range(30):
                await c.poll()
                await ms.sleep(0.15)
            results.append(c._generation)
            await c.close()

        n1 = h.create_node().name("c1").ip("10.0.6.3").build()
        n2 = h.create_node().name("c2").ip("10.0.6.4").build()
        g1: list = []
        g2: list = []
        j1 = n1.spawn(pair("10.0.6.3", g1))
        j2 = n2.spawn(pair("10.0.6.4", g2))
        await j1
        await j2
        # both settled on the same generation, and it stayed small
        # (2 joins = 2 bumps; churn would push it to ~30+)
        assert g1[0] == g2[0], (g1, g2)
        assert g1[0] <= 3, f"generation churn: {g1[0]}"
        return True

    assert run(3, main) is True


def test_kafka_group_picks_up_topic_created_after_subscribe():
    """Subscribing before the topic exists must not starve the member:
    topic creation rebalances the groups subscribed to it."""

    async def main():
        h = ms.Handle.current()

        async def serve():
            await kafka.SimBroker().serve("0.0.0.0:9092")

        h.create_node().name("broker").ip("10.0.7.1").init(serve).build()
        addr = "10.0.7.1:9092"

        consumer_node = h.create_node().name("c").ip("10.0.7.2").build()
        admin_node = h.create_node().name("a").ip("10.0.7.3").build()

        async def consume():
            cfg = (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("group.id", "g")
                .set("auto.offset.reset", "earliest")
                .set("heartbeat.interval.ms", "100")
            )
            c = await cfg.create(kafka.BaseConsumer)
            await c.subscribe(["later"])  # topic does not exist yet
            assert c.assignment() == []
            got = []
            for _ in range(60):
                m = await c.poll()
                if m is not None:
                    got.append(int(m.payload))
                await ms.sleep(0.15)
            await c.close()
            return got

        async def create_and_produce():
            await ms.sleep(1.0)  # consumer subscribed first
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            a = await cfg.create(kafka.AdminClient)
            await a.create_topics([kafka.NewTopic("later", 2)])
            p = await cfg.create(kafka.FutureProducer)
            for i in range(6):
                await p.send(kafka.BaseRecord.to("later").set_payload(str(i)))

        j = consumer_node.spawn(consume())
        await admin_node.spawn(create_and_produce())
        got = await j
        assert sorted(got) == list(range(6)), got
        return True

    assert run(13, main) is True
