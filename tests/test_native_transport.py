"""Native C++ transport (native/transport.cpp) and its interop with the
asyncio std backend — both speak the same wire format (C26 parity)."""

import asyncio
import shutil

import pytest

from madsim_tpu.std import native as native_mod
from madsim_tpu.std import net as std_net

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)


def run(coro):
    return asyncio.run(coro)


def test_native_to_native_roundtrip():
    async def main():
        a = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        b = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        try:
            await a.send_to(("127.0.0.1", b.local_addr[1]), 5, {"x": [1, 2, 3]})
            payload, src = await b.recv_from(5, timeout=5)
            assert payload == {"x": [1, 2, 3]}
            # reply to the announced canonical source
            await b.send_to(src, 6, "pong")
            payload2, _ = await a.recv_from(6, timeout=5)
            assert payload2 == "pong"
        finally:
            a.close()
            b.close()

    run(main())


def test_native_recv_timeout():
    async def main():
        a = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        try:
            with pytest.raises(asyncio.TimeoutError):
                await a.recv_from(1, timeout=0.2)
        finally:
            a.close()

    run(main())


def test_native_interops_with_asyncio_backend():
    """A native endpoint and an asyncio endpoint exchange messages over
    the shared wire format, both directions."""

    async def main():
        py = await std_net.Endpoint.bind("127.0.0.1:0")
        cc = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        try:
            # native -> python
            await cc.send_to(("127.0.0.1", py.local_addr[1]), 9, [1, "two", 3.0])
            payload, src = await py.recv_from(9)
            assert payload == [1, "two", 3.0]
            assert src[1] == cc.local_addr[1]
            # python -> native (reply path through the announced addr)
            await py.send_to(src, 10, {"ok": True})
            payload2, src2 = await cc.recv_from(10, timeout=5)
            assert payload2 == {"ok": True}
            assert src2[1] == py.local_addr[1]
        finally:
            cc.close()
            await py.close()

    run(main())


def test_native_many_messages_ordered_per_tag():
    async def main():
        a = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        b = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        try:
            for i in range(100):
                await a.send_to(("127.0.0.1", b.local_addr[1]), 1, i)
            got = [
                (await b.recv_from(1, timeout=5))[0] for _ in range(100)
            ]
            assert got == list(range(100))
        finally:
            a.close()
            b.close()

    run(main())
