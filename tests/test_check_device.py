"""Device-resident verification (check/device.py + ISSUE 14 wiring).

Four layers under test, mirroring the module stack:

* the **oracle table** — hand-built per-detector fixtures covering the
  rank-matching guard paths (paired invoke / bare response / malformed
  invoke-after — previously exercised only indirectly via soaks),
  asserted against the numpy detectors (the authoritative oracle) AND
  the jnp kernels (the port must match the oracle bit for bit);
* the **engine identity** — `search_seeds(device_check=...)` ==
  `history_invariant` verdicts on recorded models, clean and
  planted-mutant, lockstep and compacted (the layout matrix rides the
  slow tier);
* **prefix-compaction** — the fold is loud and lossless, flagged seeds
  ship verbatim-full histories, and the escalated history fails the
  exact Wing–Gong checker (the PR-1 cross-check);
* the **device history hunt** — `explore.run_device(history_check=)`
  is bit-identical to the host driver and its finds replay there.

Seed counts are lean here; tools/verify_bench.py runs the same pins at
the 65k evidence scale (VERIFY_r09.txt).
"""

import numpy as np
import pytest

import jax

from madsim_tpu.check import BatchHistory, device as dc
from madsim_tpu.check import vectorized as v
from madsim_tpu.check.history import (
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    OP_READ,
    OP_USER,
    OP_WRITE,
)
from madsim_tpu.check.linearize import check_kv
from madsim_tpu.engine import EngineConfig, make_init, search_seeds
from madsim_tpu.engine.compact import make_run_compacted
from madsim_tpu.models import make_kvchaos, make_raft, make_raftlog
from madsim_tpu.models.raft import OP_ELECT
from madsim_tpu.models.raftlog import OP_RECOVER, OP_SYNCED

CFG = EngineConfig(pool_size=40, loss_p=0.02,
                   clog_backoff_max_ns=2_000_000_000)
KV_SCREENS = (dc.stale_reads(), dc.read_your_writes(),
              dc.monotonic_reads())
KV_INV = dc.screens_invariant(KV_SCREENS)


def _hist(*seeds):
    """Synthetic BatchHistory: each seed a list of
    (op, key, arg, client, ok) records in buffer order (t = index)."""
    s = len(seeds)
    h = max((len(rows) for rows in seeds), default=0)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    count = np.zeros((s,), np.int32)
    for i, rows in enumerate(seeds):
        count[i] = len(rows)
        for j, rec in enumerate(rows):
            word[i, j] = rec
            t[i, j] = j
    return BatchHistory(word=word, t=t, count=count,
                        drop=np.zeros((s,), np.int32))


def _device(screens, h: BatchHistory) -> np.ndarray:
    ok = jax.jit(
        lambda w, t, c, d: dc.screen_ok(screens, w, t, c, d)
    )(h.word, h.t, h.count, h.drop)
    return np.asarray(ok)


def _both(screen, h):
    """(numpy verdicts, device verdicts) for one screen."""
    return np.asarray(screen.host(h), bool), _device((screen,), h)


# -------------------------------------------------- the oracle table
# Each fixture: (name, screen, history rows, expected verdict). The
# rank-matching guard paths are the point: a response's floor comes
# from its PAIRED invoke (earlier invoke), its OWN slot (no invoke —
# a bare/instantaneous event), or nowhere (rank-matched invoke AFTER
# the response: malformed, under-flag not false-flag).
W, R = OP_WRITE, OP_READ
ORACLE = [
    # paired invoke: write completes while the read is in flight —
    # floor sampled at the INVOKE, so the newer write never false-flags
    ("stale/paired-invoke-in-flight-write", dc.stale_reads(),
     [(W, 0, 1, 0, OK_OK), (R, 0, 0, 1, OK_PENDING),
      (W, 0, 2, 0, OK_OK), (R, 0, 1, 1, OK_OK)],
     True),
    # the same read missing the write completed BEFORE its invoke
    ("stale/paired-invoke-lost-write", dc.stale_reads(),
     [(W, 0, 1, 0, OK_OK), (W, 0, 2, 0, OK_OK),
      (R, 0, 0, 1, OK_PENDING), (R, 0, 1, 1, OK_OK)],
     False),
    # bare response (no invoke record anywhere): floor at its OWN
    # buffer slot — the write before it counts
    ("stale/bare-response-floor-at-own-slot", dc.stale_reads(),
     [(W, 0, 2, 0, OK_OK), (R, 0, 1, 1, OK_OK)],
     False),
    ("stale/bare-response-clean", dc.stale_reads(),
     [(W, 0, 2, 0, OK_OK), (R, 0, 2, 1, OK_OK)],
     True),
    # malformed: the rank-matched invoke sits AFTER the response —
    # no constraint (under-flag, never false-flag)
    ("stale/invoke-after-response-unconstrained", dc.stale_reads(),
     [(W, 0, 2, 0, OK_OK), (R, 0, 0, 1, OK_OK),
      (R, 0, 9, 1, OK_PENDING)],
     True),
    # failed responses never sample the floor
    ("stale/failed-read-unconstrained", dc.stale_reads(),
     [(W, 0, 2, 0, OK_OK), (R, 0, 0, 1, OK_PENDING),
      (R, 0, 0, 1, OK_FAIL)],
     True),
    # read-your-writes scopes the floor to the client's OWN writes
    ("ryw/other-clients-write-ignored", dc.read_your_writes(),
     [(W, 0, 5, 0, OK_OK), (R, 0, 0, 1, OK_PENDING),
      (R, 0, 0, 1, OK_OK)],
     True),
    ("ryw/own-write-enforced", dc.read_your_writes(),
     [(W, 0, 5, 1, OK_OK), (R, 0, 0, 1, OK_PENDING),
      (R, 0, 0, 1, OK_OK)],
     False),
    # invoke-interval-aware monotonic reads: pipelined reads (two open
    # at once) may legally complete out of order
    ("monotonic/pipelined-out-of-order-ok", dc.monotonic_reads(),
     [(R, 0, 0, 0, OK_PENDING), (R, 0, 0, 0, OK_PENDING),
      (R, 0, 2, 0, OK_OK), (R, 0, 1, 0, OK_OK)],
     True),
    # ...but the strict response-order pass flags exactly that
    ("monotonic-strict/flags-pipelined", dc.monotonic_reads_strict(),
     [(R, 0, 0, 0, OK_PENDING), (R, 0, 0, 0, OK_PENDING),
      (R, 0, 2, 0, OK_OK), (R, 0, 1, 0, OK_OK)],
     False),
    # sequential session regression IS flagged by the sound pass
    ("monotonic/sequential-regression", dc.monotonic_reads(),
     [(R, 0, 0, 0, OK_PENDING), (R, 0, 2, 0, OK_OK),
      (R, 0, 0, 0, OK_PENDING), (R, 0, 1, 0, OK_OK)],
     False),
    # election safety: two winners of one term
    ("election/two-winners", dc.election_safety(OP_USER),
     [(OP_USER, 3, 1, 1, OK_OK), (OP_USER, 3, 2, 2, OK_OK)],
     False),
    ("election/re-record-same-winner", dc.election_safety(OP_USER),
     [(OP_USER, 3, 1, 1, OK_OK), (OP_USER, 3, 1, 1, OK_OK),
      (OP_USER, 4, 2, 2, OK_OK)],
     True),
    # recovery safety: floor is the LAST sync, not the running max —
    # a legitimately truncated-then-synced length recovers clean
    ("recovery/truncation-resync-ok",
     dc.recovery_safety(OP_USER + 2, OP_USER + 3),
     [(OP_USER + 2, 0, 5, 1, OK_OK), (OP_USER + 2, 0, 3, 1, OK_OK),
      (OP_USER + 3, 0, 3, 1, OK_OK)],
     True),
    ("recovery/regression-flagged",
     dc.recovery_safety(OP_USER + 2, OP_USER + 3),
     [(OP_USER + 2, 0, 5, 1, OK_OK), (OP_USER + 3, 0, 2, 1, OK_OK)],
     False),
    ("recovery/other-node-sync-ignored",
     dc.recovery_safety(OP_USER + 2, OP_USER + 3),
     [(OP_USER + 2, 0, 5, 2, OK_OK), (OP_USER + 3, 0, 0, 1, OK_OK)],
     True),
]


class TestOracleTable:
    """The per-detector oracle table: numpy == expected (the direct
    unit fixtures the rank-matching guard paths never had) and
    device == numpy (the port pin)."""

    @pytest.mark.parametrize(
        "name,screen,rows,expect", ORACLE, ids=[o[0] for o in ORACLE]
    )
    def test_fixture(self, name, screen, rows, expect):
        h = _hist(rows)
        host, dev = _both(screen, h)
        assert host[0] == expect, f"numpy oracle drifted on {name}"
        assert dev[0] == expect, f"device kernel differs on {name}"

    def test_fuzz_device_equals_numpy_all_detectors(self):
        rng = np.random.default_rng(42)
        s, hd = 128, 24
        word = np.zeros((s, hd, 5), np.int32)
        word[:, :, 0] = rng.integers(1, 4, (s, hd))
        word[:, :, 1] = rng.integers(0, 3, (s, hd))
        word[:, :, 2] = rng.integers(0, 6, (s, hd))
        word[:, :, 3] = rng.integers(0, 3, (s, hd))
        word[:, :, 4] = rng.integers(-1, 2, (s, hd))
        h = BatchHistory(
            word=word,
            t=np.arange(hd, dtype=np.int64)[None].repeat(s, 0),
            count=rng.integers(0, hd + 1, (s,)).astype(np.int32),
            drop=np.zeros((s,), np.int32),
        )
        screens = (
            dc.stale_reads(), dc.read_your_writes(), dc.monotonic_reads(),
            dc.monotonic_reads_strict(), dc.election_safety(3),
            dc.recovery_safety(3, 1),
        )
        for s_ in screens:
            host, dev = _both(s_, h)
            assert np.array_equal(host, dev), s_.kind
            assert not host.all() and host.any(), (
                f"degenerate fuzz for {s_.kind}: nothing to compare"
            )

    def test_overflowed_seed_judged_as_empty(self):
        h = _hist([(W, 0, 2, 0, OK_OK), (R, 0, 0, 1, OK_OK)])
        h.drop[0] = 1
        assert _device((dc.stale_reads(),), h)[0]  # quarantined clean

    def test_verdict_words_roundtrip(self):
        for n in (1, 31, 32, 33, 200):
            ok = (np.arange(n) % 3) != 0
            words = np.asarray(jax.jit(dc.pack_verdicts)(ok))
            assert words.shape == ((n + 31) // 32,)
            assert np.array_equal(dc.unpack_verdicts(words, n), ok)
            assert np.array_equal(dc.pack_verdicts_host(ok), words)

    def test_slo_breaches_matches_numpy(self):
        from madsim_tpu.check.slo import slo_breaches as host_slo
        from madsim_tpu.engine.core import N_LAT_BUCKETS

        rng = np.random.default_rng(1)
        hist = rng.integers(0, 40, (64, 3, N_LAT_BUCKETS)).astype(np.int32)
        hist[rng.random((64, 3)) < 0.3] = 0
        for bound in (10_000, 50_000_000, 10**10):
            dev = np.asarray(
                jax.jit(lambda x, b=bound: dc.slo_breaches(x, b))(hist)
            )
            assert np.array_equal(dev, host_slo(hist, bound))

    def test_screen_spec_validation(self):
        with pytest.raises(ValueError, match="unknown screen kind"):
            dc.HistoryScreen("linearizable_wing_gong")
        with pytest.raises(ValueError, match="non-empty"):
            dc.as_screens(())
        assert dc.as_screens(dc.stale_reads()) == (dc.stale_reads(),)
        # value-hashable: equal specs are one cache key
        assert hash(dc.stale_reads()) == hash(dc.stale_reads())


# ------------------------------------------- engine verdict identity
def _identity_case(wl, n_seeds, **kw):
    host = search_seeds(wl, CFG, None, history_invariant=KV_INV,
                        n_seeds=n_seeds, require_halt=False, **kw)
    dev = search_seeds(wl, CFG, None, device_check=KV_SCREENS,
                       n_seeds=n_seeds, require_halt=False, **kw)
    assert np.array_equal(host.ok, dev.ok)
    assert np.array_equal(host.overflowed, dev.overflowed)
    return host, dev


class TestEngineIdentity:
    # tier-1 budget (ROADMAP note): the host/device/compact lockstep at
    # 512 seeds x 2 bug modes is the heaviest compile in this file and
    # its verdict-identity claim stays tier-1-pinned by
    # test_fuzz_device_equals_numpy_all_detectors (all detectors),
    # test_flagged_history_is_the_escalation_input (mutant caught +
    # exact confirmation) and TestPrefixCompaction (compact verdicts);
    # the full-scale lockstep is VERIFY_r09 cert 1.
    @pytest.mark.slow
    def test_kvchaos_clean_and_mutant_lockstep_and_compact(self):
        for bug in (False, True):
            wl = make_kvchaos(writes=5, record=True, bug=bug)
            host, dev = _identity_case(wl, 512, max_steps=600)
            hostc = search_seeds(
                wl, CFG, None, history_invariant=KV_INV, n_seeds=512,
                max_steps=600, require_halt=False, compact=True,
            )
            devc = search_seeds(
                wl, CFG, None, device_check=KV_SCREENS, n_seeds=512,
                max_steps=600, require_halt=False, compact=True,
            )
            assert np.array_equal(host.ok, hostc.ok)
            assert np.array_equal(host.ok, devc.ok)
            if bug:
                assert len(dev.failing_seeds)  # the mutant is caught
                assert np.array_equal(dev.flagged_idx,
                                      np.nonzero(~host.ok)[0])

    def test_flagged_history_is_the_escalation_input(self):
        wl = make_kvchaos(writes=5, record=True, bug=True)
        dev = search_seeds(wl, CFG, None, device_check=KV_SCREENS,
                           n_seeds=512, max_steps=600,
                           require_halt=False)
        assert len(dev.flagged_idx)
        fh = dev.flagged_history
        assert fh.word.shape[0] == len(dev.flagged_idx)
        # every flagged seed's full history fails exact Wing-Gong KV —
        # the vectorized catch is exact-confirmed (PR-1 discipline)
        for i in range(len(fh)):
            assert not check_kv(fh.ops(i)).ok

    def test_api_validation(self):
        wl_plain = make_kvchaos(writes=5)
        with pytest.raises(ValueError, match="device_check"):
            search_seeds(wl_plain, CFG, None, n_seeds=4,
                         device_check=KV_SCREENS)
        wl = make_kvchaos(writes=5, record=True)
        with pytest.raises(ValueError, match="not both"):
            search_seeds(wl, CFG, None, n_seeds=4,
                         device_check=KV_SCREENS,
                         history_invariant=KV_INV)
        with pytest.raises(ValueError, match="invariant"):
            search_seeds(wl, CFG, None, n_seeds=4)

    @pytest.mark.slow
    def test_layout_matrix_2048_seeds_per_model(self):
        """The acceptance pin: >= 2048 seeds per recorded model, clean
        + planted mutant, scatter/dense/time32 + the compacted runner."""
        cases = [
            (make_kvchaos(writes=5, record=True), KV_SCREENS, KV_INV),
            (make_kvchaos(writes=5, record=True, bug=True),
             KV_SCREENS, KV_INV),
            (make_raft(record=True),
             (dc.election_safety(OP_ELECT),),
             dc.screens_invariant((dc.election_safety(OP_ELECT),))),
            (make_raftlog(record=True, durable=True),
             (dc.election_safety(OP_ELECT),
              dc.recovery_safety(OP_SYNCED, OP_RECOVER)),
             dc.screens_invariant(
                 (dc.election_safety(OP_ELECT),
                  dc.recovery_safety(OP_SYNCED, OP_RECOVER)))),
            (make_raftlog(record=True, durable=True, bug="nosync"),
             (dc.election_safety(OP_ELECT),
              dc.recovery_safety(OP_SYNCED, OP_RECOVER)),
             dc.screens_invariant(
                 (dc.election_safety(OP_ELECT),
                  dc.recovery_safety(OP_SYNCED, OP_RECOVER)))),
        ]
        for wl, screens, inv in cases:
            for lay_kw in (dict(layout="scatter"), dict(layout="dense"),
                           dict(layout="scatter", compact=True)):
                host = search_seeds(
                    wl, CFG, None, history_invariant=inv, n_seeds=2048,
                    max_steps=600, require_halt=False, **lay_kw,
                )
                dev = search_seeds(
                    wl, CFG, None, device_check=screens, n_seeds=2048,
                    max_steps=600, require_halt=False, **lay_kw,
                )
                assert np.array_equal(host.ok, dev.ok), (wl.name, lay_kw)

    @pytest.mark.slow
    def test_time32_representation_verdict_identity(self):
        """The int32-time lowering (what an accelerator runs) feeds the
        same columns to the same kernels: device == numpy under both
        representations, and the representations agree."""
        from madsim_tpu.engine.core import make_run_while

        wl = make_kvchaos(writes=5, record=True, bug=True)
        seeds = np.arange(2048, dtype=np.uint64)
        verdicts = []
        for t32 in (False, True):
            st = jax.jit(make_run_while(wl, CFG, 600, time32=t32))(
                make_init(wl, CFG, time32=t32)(seeds)
            )
            assert not np.asarray(st.overflow).any()
            dev = _device(KV_SCREENS, BatchHistory.from_state(st))
            host = KV_INV(BatchHistory.from_state(st))
            assert np.array_equal(dev, host), f"time32={t32}"
            verdicts.append(dev)
        assert np.array_equal(verdicts[0], verdicts[1])


# ---------------------------------------------- prefix-compaction
class TestPrefixCompaction:
    def test_fold_keeps_fifo_pending_invokes_only(self):
        # I1 R1 R2 I2: R1 closes I1 (FIFO), R2 is instantaneous (no
        # open invoke), I2 stays pending -> ONLY I2 survives the fold
        h = _hist([
            (W, 0, 1, 0, OK_PENDING), (W, 0, 1, 0, OK_OK),
            (W, 0, 9, 0, OK_OK), (W, 0, 2, 0, OK_PENDING),
        ])
        ok = np.asarray([True])
        w2, t2, c2, fold = jax.jit(dc.fold_verified)(
            h.word, h.t, h.count, h.drop, ok
        )
        assert int(c2[0]) == 1 and int(fold[0]) == 3
        assert tuple(np.asarray(w2)[0, 0]) == (W, 0, 2, 0, OK_PENDING)
        assert int(np.asarray(t2)[0, 0]) == 3  # original clock rides along

    def test_flagged_and_overflowed_seeds_keep_everything(self):
        rows = [(W, 0, 1, 0, OK_PENDING), (W, 0, 1, 0, OK_OK)]
        h = _hist(rows, rows)
        h.drop[1] = 2  # overflowed
        ok = np.asarray([False, True])  # flagged / overflowed-clean
        w2, t2, c2, fold = jax.jit(dc.fold_verified)(
            h.word, h.t, h.count, h.drop, ok
        )
        assert np.array_equal(np.asarray(c2), h.count)
        assert np.array_equal(np.asarray(fold), [0, 0])
        assert np.array_equal(np.asarray(w2), h.word)

    def test_compacted_runner_folds_losslessly(self):
        wl = make_kvchaos(writes=5, record=True, bug=True)
        seeds = np.arange(512, dtype=np.uint64)
        init = make_init(wl, CFG)
        plain = make_run_compacted(wl, CFG, 600)(init(seeds))
        folded = make_run_compacted(wl, CFG, 600,
                                    hist_screen=KV_SCREENS)(init(seeds))
        # loud accounting: nothing vanishes silently
        assert np.array_equal(folded.hist_count + folded.hist_fold,
                              plain.hist_count)
        assert np.array_equal(folded.hist_drop, plain.hist_drop)
        # flagged seeds verbatim-full (the escalation path)
        flag = ~folded.hist_ok
        assert flag.any() and not flag.all()
        assert np.array_equal(folded.hist_word[flag],
                              plain.hist_word[flag])
        assert np.array_equal(folded.hist_t[flag], plain.hist_t[flag])
        # clean seeds fold their responded pairs
        assert (folded.hist_fold[~flag] > 0).any()
        # the verdict equals the numpy detectors on the UNfolded columns
        bh = BatchHistory(word=plain.hist_word, t=plain.hist_t,
                          count=plain.hist_count, drop=plain.hist_drop)
        assert np.array_equal(folded.hist_ok, KV_INV(bh))

    def test_sharded_screened_runner_matches_unsharded(self):
        """The detectors run sharded WITH the sim: each device screens
        and folds its own banked rows inside shard_map, and the
        assembled result equals the unsharded screened runner."""
        from madsim_tpu import parallel

        wl = make_kvchaos(writes=5, record=True, bug=True)
        mesh = parallel.make_mesh()
        n_dev = mesh.devices.size
        seeds = np.arange(16 * n_dev, dtype=np.uint64)
        init = make_init(wl, CFG)
        base = make_run_compacted(wl, CFG, 600,
                                  hist_screen=KV_SCREENS)(init(seeds))
        sh = parallel.shard_run_compacted(
            wl, CFG, 600, mesh, hist_screen=KV_SCREENS,
        )(parallel.shard_state(init(seeds), mesh))
        for f in ("hist_ok", "hist_fold", "hist_count", "hist_word",
                  "hist_t", "trace"):
            assert np.array_equal(getattr(base, f), getattr(sh, f)), f

    def test_hist_screen_requires_history(self):
        with pytest.raises(ValueError, match="history"):
            make_run_compacted(make_kvchaos(writes=5), CFG, 100,
                               hist_screen=KV_SCREENS)


# ------------------------------------------- the device history hunt
class TestDeviceHistoryHunt:
    # tier-1 budget: the host-vs-device campaign bit-identity + replay
    # is VERIFY_r09's headline certificate (and the services soak
    # re-proves it on two more models); tier-1 keeps the API guard
    # below and the device-detector identity pins above.
    @pytest.mark.slow
    def test_run_device_history_hunt_matches_host_and_replays(self):
        from madsim_tpu import explore
        from madsim_tpu.chaos import CrashStorm, FaultPlan
        from madsim_tpu.obs import prof

        wl = make_kvchaos(writes=5, record=True, bug=True)
        plan = FaultPlan((CrashStorm(targets=(1, 2, 3, 4), n=2),),
                         name="hunt")
        kw = dict(generations=2, batch=64, root_seed=7, max_steps=600,
                  cov_words=16)
        host = explore.run(wl, CFG, plan, invariant=None,
                           history_invariant=KV_INV, **kw)
        profiler = prof.ProgramProfiler()
        with prof.profiled(profiler):
            dev = explore.run_device(wl, CFG, plan, invariant=None,
                                     history_check=KV_SCREENS, **kw)
        # bit-identical campaign: corpus, coverage, violations
        assert [
            (e.id, e.seed, e.trace, e.violating, e.plan.hash())
            for e in host.corpus
        ] == [
            (e.id, e.seed, e.trace, e.violating, e.plan.hash())
            for e in dev.corpus
        ]
        assert np.array_equal(host.cov_map, dev.cov_map)
        assert [(e.seed, e.trace) for e in host.violations] == [
            (e.seed, e.trace) for e in dev.violations
        ]
        # the hunt finds the lost-write mutant, device-resident
        assert dev.violations
        # one trace per (key, mode): the screen joined the cached
        # generation program without defeating the cache
        retr = profiler.retraces("explore.device")
        assert retr and all(n == 1 for n in retr.values())
        # the find replays on the HOST driver, trace + verdict identical
        e = dev.violations[0]
        rep = explore.replay_entry(wl, CFG, e, history_invariant=KV_INV,
                                   max_steps=600)
        assert int(rep.traces[0]) == e.trace and not bool(rep.ok[0])

    def test_run_device_requires_some_check(self):
        from madsim_tpu import explore
        from madsim_tpu.chaos import CrashStorm, FaultPlan

        plan = FaultPlan((CrashStorm(targets=(1, 2),),), name="p")
        with pytest.raises(ValueError, match="invariant"):
            explore.run_device(make_kvchaos(writes=5, record=True), CFG,
                               plan, invariant=None)
        with pytest.raises(ValueError, match="history_check"):
            explore.run_device(make_kvchaos(writes=5), CFG, plan,
                               invariant=None,
                               history_check=KV_SCREENS)


# ------------------------------------------------ cov_features hook
class TestCovFeatures:
    # tier-1 budget: bitmap-growth-without-trace-change is re-pinned
    # cheaply by test_obs's hit-count rows and the lint
    # noninterference coverage axes; the spread deltas are EXPLORE_r08.
    @pytest.mark.slow
    def test_commit_spread_changes_bitmaps_not_traces(self):
        inv = lambda view: np.ones(  # noqa: E731
            np.asarray(view["halted"]).shape[0], bool
        )
        base = search_seeds(make_raftlog(record=True), CFG, inv,
                            n_seeds=96, max_steps=400, cov_words=16,
                            require_halt=False)
        hooked = search_seeds(
            make_raftlog(record=True, cov_spread=True), CFG, inv,
            n_seeds=96, max_steps=400, cov_words=16, require_halt=False,
        )
        # coverage is derived state: the hook must not move the sim
        assert np.array_equal(base.traces, hooked.traces)
        assert np.array_equal(base.halted, hooked.halted)
        # ...but it must contribute fresh guidance bits
        extra = (np.bitwise_or.reduce(hooked.cov, axis=0)
                 & ~np.bitwise_or.reduce(base.cov, axis=0))
        assert extra.any()


# -------------------------------------------------- sharded folds
class TestMergeVerdicts:
    def test_merge_verdicts_packs_seed_order(self):
        from madsim_tpu import parallel

        ok = (np.arange(256) % 5) != 0
        words = parallel.merge_verdicts(ok)
        assert np.array_equal(dc.unpack_verdicts(words, 256), ok)
        mesh = parallel.make_mesh()
        if 256 % (mesh.devices.size * 32) == 0:
            sharded = parallel.merge_verdicts(ok, mesh)
            assert np.array_equal(sharded, words)

    def test_merge_verdicts_rejects_misaligned(self):
        from madsim_tpu import parallel

        mesh = parallel.make_mesh()
        if mesh.devices.size > 1:
            with pytest.raises(ValueError, match="word-aligned"):
                parallel.merge_verdicts(np.ones(mesh.devices.size, bool),
                                        mesh)
