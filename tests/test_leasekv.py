"""Lease/watch KV (models/leasekv.py) + check.lease_safety.

Pins, per the round's contract: the detector's oracle table on
synthetic histories (both clauses, the re-grant escape hatch, the
under-flag cases) with the jnp HistoryScreen bit-identical to the
numpy form on every table row; a deterministic grant-after-expiry
scenario where ``bug=True`` is flagged on EVERY seed and the clean
model on none (again numpy == device); dual-mode convergence of the
batched lease machine against the single-seed ``services/etcd.py``
server on the same stalled-keepalive scenario; layout/time32/compact
bit-determinism; and checkpoint save/resume identity. Soak-scale
hunts (device-resident screens, shrink, replay) live in
tools/services_model_soak.py (SERVICES_MODELS_r12.txt)."""

import numpy as np
import pytest

import jax

from madsim_tpu import check
from madsim_tpu.check import device as dc
from madsim_tpu.check.history import OK_FAIL, OK_OK, BatchHistory
from madsim_tpu.engine import (
    EngineConfig,
    load_checkpoint,
    make_init,
    make_run,
    make_run_compacted,
    save_checkpoint,
    search_seeds,
)
from madsim_tpu.engine.verify import check_layouts
from madsim_tpu.models.leasekv import OP_EXPIRE, OP_PUT, OP_WATCH_EVT, make_leasekv

SCREENS = (dc.lease_safety(OP_PUT, OP_EXPIRE),)


def _hist(*seeds):
    """Synthetic BatchHistory: each seed a list of
    (op, key, arg, client, ok, t) records in buffer order."""
    s = len(seeds)
    h = max((len(rows) for rows in seeds), default=0)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    count = np.zeros((s,), np.int32)
    for i, rows in enumerate(seeds):
        count[i] = len(rows)
        for j, (op, key, arg, client, ok, ts) in enumerate(rows):
            word[i, j] = (op, key, arg, client, ok)
            t[i, j] = ts
    return BatchHistory(word=word, t=t, count=count,
                        drop=np.zeros((s,), np.int32))


def _both(h):
    """numpy ok-mask and the device HistoryScreen's, asserted equal."""
    host = check.lease_safety(h, OP_PUT, OP_EXPIRE)
    dev = np.asarray(dc.screen_ok(SCREENS, h.word, h.t, h.count, h.drop))
    assert np.array_equal(host, dev), "numpy and jnp detectors disagree"
    return host


# grant / expiry / serve record shorthands (server = client 0 in the
# record convention; key = lease id, lifecycle on OP_EXPIRE)
def _grant(lid, deadline, t=0):
    return (OP_EXPIRE, lid, deadline, 0, OK_OK, t)


def _expire(lid, at_ms, t=0):
    return (OP_EXPIRE, lid, at_ms, 0, OK_FAIL, t)


def _serve(lid, seq, t=0):
    return (OP_PUT, lid, seq, 0, OK_OK, t)


class TestLeaseSafetyOracle:
    """The detector's truth table, host and device forms together."""

    def test_clean_lifecycle_ok(self):
        h = _hist([_grant(1, 500), _serve(1, 1), _expire(1, 500)])
        assert _both(h).tolist() == [True]

    def test_serve_after_expiry_flagged(self):
        h = _hist([_grant(1, 500), _expire(1, 500), _serve(1, 1)])
        assert _both(h).tolist() == [False]

    def test_regrant_between_expiry_and_serve_ok(self):
        # the clean rejoin path: expiry, re-grant, THEN serve
        h = _hist([_grant(1, 500), _expire(1, 500),
                   _grant(1, 900), _serve(1, 2)])
        assert _both(h).tolist() == [True]

    def test_other_leases_expiry_does_not_flag(self):
        # lifecycle records are per lease id: lease 2 dying says
        # nothing about lease 1's serves
        h = _hist([_grant(1, 500), _grant(2, 500),
                   _expire(2, 500), _serve(1, 1)])
        assert _both(h).tolist() == [True]

    def test_early_expiry_flagged(self):
        # clause 2: the server expired the lease before its own clock
        # reached the deadline it granted
        h = _hist([_grant(1, 500), _expire(1, 499)])
        assert _both(h).tolist() == [False]

    def test_skewed_but_honest_expiry_ok(self):
        # expiry strictly after the granted deadline on the server's
        # local clock is the contract — skew never flags by itself
        h = _hist([_grant(1, 500), _expire(1, 777)])
        assert _both(h).tolist() == [True]

    def test_serve_with_no_lifecycle_constrains_nothing(self):
        h = _hist([_serve(1, 1)])
        assert _both(h).tolist() == [True]

    def test_per_seed_verdicts_independent(self):
        h = _hist(
            [_grant(1, 500), _expire(1, 500), _serve(1, 1)],  # clause 1
            [_grant(1, 500), _serve(1, 1), _expire(1, 500)],  # clean
            [_grant(1, 500), _expire(1, 400)],  # clause 2
            [],  # empty history
        )
        assert _both(h).tolist() == [False, True, False, True]


# ---------------------------------------------------------------------------
# the deterministic grant-after-expiry scenario
# ---------------------------------------------------------------------------

# keepalives SLOWER than the TTL (ka 80ms vs ttl 50ms): every lease
# expires between heartbeats, so every keepalive lands on a dead lease.
# The fast put timer (30ms < ttl) keeps the clean model progressing —
# one put is always served inside each fresh grant's window, the
# rejected ones trigger the re-grant path, and the history shows
# expiry -> grant -> serve everywhere. bug=True: the keepalive
# silently resurrects the dead lease, so some put is served with the
# expiry as its latest lifecycle record — every seed flagged.
_SCEN = dict(ttl_ms=50, ka_ms=80, scan_ms=20, put_ms=30,
             chaos=False, record=True)
_CFG = EngineConfig(pool_size=48, loss_p=0.0)
_N_SEEDS = 8
_STEPS = 900

_SHARED: dict = {}


def _scenario(bug):
    key = "bug" if bug else "clean"
    if key not in _SHARED:
        box = {}

        def hinv(h):
            box["h"] = h
            return np.ones(len(h.count), bool)

        rep = search_seeds(
            make_leasekv(bug=bug, **_SCEN), _CFG, None,
            n_seeds=_N_SEEDS, max_steps=_STEPS, history_invariant=hinv,
        )
        _SHARED[key] = (rep, box["h"])
    return _SHARED[key]


class TestMutantScenario:
    def test_clean_model_is_clean(self):
        rep, h = _scenario(bug=False)
        assert rep.ok.all(), rep.failing_seeds
        assert _both(h).all()

    def test_mutant_flagged_on_every_seed(self):
        rep, h = _scenario(bug=True)
        assert rep.halted.all(), "mutant scenario must still halt"
        assert not _both(h).any(), (
            "grant-after-expiry mutant escaped the detector"
        )

    def test_screens_invariant_matches_direct_call(self):
        _, h = _scenario(bug=True)
        inv = dc.screens_invariant(SCREENS)
        assert np.array_equal(np.asarray(inv(h)), _both(h))


# ---------------------------------------------------------------------------
# dual-mode convergence: batched lease machine vs services/etcd.py
# ---------------------------------------------------------------------------


class TestDualModeConvergence:
    """One scenario, two arms: client 1 stalls its keepalives at 2s
    while clients 2/3 keep renewing a 5s-TTL lease. The batched model
    (``ka_stop_ms``) and the single-seed etcd server (``tick()``) must
    reach the same verdict — lease 1 expires, leases 2/3 survive."""

    TTL_S, STALL_S, END_S = 5, 2, 12

    def _host_arm(self):
        import random

        from madsim_tpu.services.etcd import _ServiceInner

        inner = _ServiceInner()
        rng = random.Random(0)
        for lid in (1, 2, 3):
            inner.lease_grant(self.TTL_S, lid, rng)
        expired_at = {}
        for t in range(1, self.END_S + 1):
            for lid in list(inner.leases):
                if not (lid == 1 and t >= self.STALL_S):
                    inner.lease_keep_alive(lid)
            before = set(inner.leases)
            inner.tick()
            for lid in before - set(inner.leases):
                expired_at[lid] = t
        return expired_at, set(inner.leases)

    def _batched_arm(self):
        wl = make_leasekv(
            ttl_ms=self.TTL_S * 1000, ka_ms=1000, scan_ms=1000,
            put_ms=1_000_000, ka_stop_ms=self.STALL_S * 1000,
            chaos=False, record=True,
        )
        box = {}

        def hinv(h):
            box["h"] = h
            return np.ones(len(h.count), bool)

        search_seeds(
            wl, EngineConfig(pool_size=48, loss_p=0.0), None,
            n_seeds=1, max_steps=140, require_halt=False,
            history_invariant=hinv,
        )
        h = box["h"]
        valid = h.valid()[0]
        word = h.word[0]
        life = valid & (word[:, 0] == OP_EXPIRE)
        exp = life & (word[:, 4] == OK_FAIL)
        granted = {int(k) for k in word[life & (word[:, 4] == OK_OK), 1]}
        expired_at = {
            int(k): int(a) // 1000
            for k, a in zip(word[exp, 1], word[exp, 2])
        }
        wevt = valid & (word[:, 0] == OP_WATCH_EVT) & (word[:, 4] == OK_OK)
        return h, granted, expired_at, {int(k) for k in word[wevt, 1]}

    def test_verdicts_converge(self):
        host_expired, host_alive = self._host_arm()
        h, granted, batched_expired, watched = self._batched_arm()
        # identical verdicts: WHICH leases died and which survived
        assert set(host_expired) == set(batched_expired) == {1}
        assert host_alive == granted - set(batched_expired) == {2, 3}
        # the expiry instant agrees up to the two arms' discretization:
        # the host tick expires at remaining<=1 (one second early
        # against the ms deadline) and the batched scan quantizes the
        # deadline up to the next whole-second scan after 1-10ms of
        # network latency on the renewal — a fixed <=2s window, never
        # a drifting one
        for lid, host_t in host_expired.items():
            assert 0 <= batched_expired[lid] - host_t <= 2
        # the watcher saw the delete event for exactly the dead lease
        assert watched == {1}
        # and the batched arm's own history is clean under the detector
        assert _both(h).all()


# ---------------------------------------------------------------------------
# determinism + checkpoint
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_layouts_time32_bit_identical(self):
        # dense/scatter x time32 lowerings of the recorded model
        check_layouts(
            make_leasekv(record=True), _CFG,
            np.arange(4, dtype=np.uint64), 400,
        )

    def test_compacted_equals_lockstep(self):
        wl = make_leasekv(record=True)
        init = make_init(wl, _CFG)
        seeds = np.arange(8, dtype=np.uint64)
        ref = jax.jit(make_run(wl, _CFG, 900))(init(seeds))
        out = make_run_compacted(wl, _CFG, 900, min_size=4)(init(seeds))
        for f in ("now", "halted", "trace", "node_state",
                  "hist_word", "hist_t", "hist_count", "hist_drop"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
                err_msg=f,
            )

    def test_checkpoint_roundtrip_resumes_identically(self, tmp_path):
        wl = make_leasekv(record=True)
        init = make_init(wl, _CFG)
        st = init(np.arange(4, dtype=np.uint64))
        run_half = jax.jit(make_run(wl, _CFG, 150))
        mid = run_half(st)
        path = str(tmp_path / "leasekv.npz")
        save_checkpoint(path, mid, _CFG)
        resumed = load_checkpoint(path, _CFG)
        a, b = run_half(mid), run_half(resumed)
        for f in ("trace", "now", "node_state", "hist_word", "hist_count"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f,
            )


def test_bug_requires_record():
    with pytest.raises(ValueError, match="record=True"):
        make_leasekv(bug=True)
