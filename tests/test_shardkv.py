"""Sharded KV with key-range migration (models/shardkv.py, the first
N=12+ model) + check.shard_coverage.

Pins, per the round's contract: the detector's oracle table on
synthetic histories (double-serve per epoch, lost-range installs, the
benign same-group and fresher-version cases) with the jnp
HistoryScreen bit-identical to the numpy form on every row; the
packed ownership word round-trips; the clean 14-node model halts
clean under loss + kills while ``bug=True`` (release-before-ack) is
caught by clause 2 (numpy == device again); layout/time32/compact
bit-determinism; and checkpoint save/resume identity. The N=17
campaign is ``slow``; soak-scale hunts live in
tools/services_model_soak.py (SERVICES_MODELS_r12.txt)."""

import numpy as np
import pytest

import jax

from madsim_tpu import check
from madsim_tpu.check import device as dc
from madsim_tpu.check.history import (
    OK_OK,
    SHARD_EPOCH_SHIFT,
    SHARD_GROUP_MASK,
    SHARD_GROUP_SHIFT,
    SHARD_VER_MASK,
    BatchHistory,
    pack_shard_own,
)
from madsim_tpu.engine import (
    EngineConfig,
    load_checkpoint,
    make_init,
    make_run,
    make_run_compacted,
    save_checkpoint,
    search_seeds,
)
from madsim_tpu.engine.verify import check_layouts
from madsim_tpu.models.shardkv import OP_SHARD_OWN, OP_SHARD_WRITE, make_shardkv

SCREENS = (dc.shard_coverage(OP_SHARD_OWN, OP_SHARD_WRITE),)
# the soak's hunt config: enough loss that retried handoffs happen
_CFG = EngineConfig(pool_size=64, loss_p=0.02,
                    clog_backoff_max_ns=2_000_000_000)


def _hist(*seeds):
    """Synthetic BatchHistory: each seed a list of
    (op, key, arg, client, ok, t) records in buffer order."""
    s = len(seeds)
    h = max((len(rows) for rows in seeds), default=0)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    count = np.zeros((s,), np.int32)
    for i, rows in enumerate(seeds):
        count[i] = len(rows)
        for j, (op, key, arg, client, ok, ts) in enumerate(rows):
            word[i, j] = (op, key, arg, client, ok)
            t[i, j] = ts
    return BatchHistory(word=word, t=t, count=count,
                        drop=np.zeros((s,), np.int32))


def _both(h):
    """numpy ok-mask and the device HistoryScreen's, asserted equal."""
    host = check.shard_coverage(h, OP_SHARD_OWN, OP_SHARD_WRITE)
    dev = np.asarray(dc.screen_ok(SCREENS, h.word, h.t, h.count, h.drop))
    assert np.array_equal(host, dev), "numpy and jnp detectors disagree"
    return host


def _own(shard, epoch, group, ver, t=0):
    return (OP_SHARD_OWN, shard, pack_shard_own(epoch, group, ver),
            0, OK_OK, t)


def _write(shard, ver, t=0):
    return (OP_SHARD_WRITE, shard, ver, 0, OK_OK, t)


def test_pack_shard_own_roundtrips():
    w = pack_shard_own(37, 11, 4321)
    assert (w >> SHARD_EPOCH_SHIFT) == 37
    assert ((w >> SHARD_GROUP_SHIFT) & SHARD_GROUP_MASK) == 11
    assert (w & SHARD_VER_MASK) == 4321
    # array form (the detectors unpack whole columns at once)
    arr = pack_shard_own(np.int32(255), np.int32(15), np.int32(0xFFFF))
    assert arr > 0, "caps must keep the packed word positive in int32"


class TestShardCoverageOracle:
    """The detector's truth table, host and device forms together."""

    def test_clean_migration_ok(self):
        h = _hist([_own(0, 1, 0, 0), _write(0, 1), _write(0, 2),
                   _own(0, 2, 1, 2), _write(0, 3)])
        assert _both(h).tolist() == [True]

    def test_double_serve_same_epoch_flagged(self):
        h = _hist([_own(0, 1, 0, 0), _own(0, 1, 1, 0)])
        assert _both(h).tolist() == [False]

    def test_same_group_reinstall_ok(self):
        # a retried install at the same group is idempotent, not a
        # double-serve
        h = _hist([_own(0, 1, 0, 0), _own(0, 1, 0, 0)])
        assert _both(h).tolist() == [True]

    def test_same_group_across_epochs_ok(self):
        h = _hist([_own(0, 1, 0, 0), _own(0, 2, 1, 0), _own(0, 3, 0, 0)])
        assert _both(h).tolist() == [True]

    def test_lost_range_flagged(self):
        # clause 2: the install adopted a version below a committed
        # write earlier in the history — the handoff shipped stale state
        h = _hist([_write(0, 3), _own(0, 2, 1, 2)])
        assert _both(h).tolist() == [False]

    def test_install_covering_writes_ok(self):
        h = _hist([_write(0, 3), _own(0, 2, 1, 3)])
        assert _both(h).tolist() == [True]

    def test_other_shards_writes_do_not_flag(self):
        h = _hist([_write(0, 5), _own(1, 2, 1, 0)])
        assert _both(h).tolist() == [True]

    def test_per_seed_verdicts_independent(self):
        h = _hist(
            [_own(0, 1, 0, 0), _own(0, 1, 1, 0)],  # clause 1
            [_write(0, 3), _own(0, 2, 1, 3)],  # clean
            [_write(0, 3), _own(0, 2, 1, 0)],  # clause 2
            [],  # empty history
        )
        assert _both(h).tolist() == [False, True, False, True]


# ---------------------------------------------------------------------------
# the lost-shard mutant under loss + kills
# ---------------------------------------------------------------------------

_N_SEEDS = 48
_STEPS = 6000

_SHARED: dict = {}


def _campaign(bug):
    key = "bug" if bug else "clean"
    if key not in _SHARED:
        box = {}

        def hinv(h):
            box["h"] = h
            return np.ones(len(h.count), bool)

        rep = search_seeds(
            make_shardkv(record=True, bug=bug), _CFG, None,
            n_seeds=_N_SEEDS, max_steps=_STEPS, history_invariant=hinv,
        )
        _SHARED[key] = (rep, box["h"])
    return _SHARED[key]


class TestMutantCampaign:
    def test_clean_model_halts_clean(self):
        # loss + the internal primary-kill chaos: every migration still
        # completes (the controller re-drives it) and the history is
        # clean — the liveness AND safety half of the contract
        rep, h = _campaign(bug=False)
        assert rep.ok.all(), rep.failing_seeds
        assert rep.halted.all(), "a wedged migration is a liveness bug"
        assert _both(h).all()

    def test_mutant_caught_by_lost_range_clause(self):
        # release-before-ack: a retried handoff re-sends from the
        # wiped source, the destination installs version 0 below the
        # committed writes. Needs loss to trigger, so assert the
        # violation rate, not per-seed determinism (52% of seeds at
        # this config in the soak's 256-seed validation)
        _, h = _campaign(bug=True)
        flagged = int((~_both(h)).sum())
        assert flagged >= _N_SEEDS // 8, (
            f"lost-shard mutant nearly escaped: {flagged}/{_N_SEEDS}"
        )


# ---------------------------------------------------------------------------
# determinism + checkpoint
# ---------------------------------------------------------------------------

_PIN_CFG = EngineConfig(pool_size=64, loss_p=0.0)


class TestDeterminism:
    def test_layouts_time32_bit_identical(self):
        check_layouts(
            make_shardkv(record=True, chaos=False), _PIN_CFG,
            np.arange(4, dtype=np.uint64), 500,
        )

    def test_compacted_equals_lockstep(self):
        wl = make_shardkv(record=True, chaos=False)
        init = make_init(wl, _PIN_CFG)
        seeds = np.arange(8, dtype=np.uint64)
        ref = jax.jit(make_run(wl, _PIN_CFG, 2500))(init(seeds))
        out = make_run_compacted(wl, _PIN_CFG, 2500, min_size=4)(init(seeds))
        for f in ("now", "halted", "trace", "node_state",
                  "hist_word", "hist_t", "hist_count", "hist_drop"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
                err_msg=f,
            )

    def test_checkpoint_roundtrip_resumes_identically(self, tmp_path):
        wl = make_shardkv(record=True)
        init = make_init(wl, _CFG)
        st = init(np.arange(4, dtype=np.uint64))
        run_half = jax.jit(make_run(wl, _CFG, 400))
        mid = run_half(st)
        path = str(tmp_path / "shardkv.npz")
        save_checkpoint(path, mid, _CFG)
        resumed = load_checkpoint(path, _CFG)
        a, b = run_half(mid), run_half(resumed)
        for f in ("trace", "now", "node_state", "hist_word", "hist_count"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f,
            )


class TestShapeValidation:
    def test_bug_requires_record(self):
        with pytest.raises(ValueError, match="record=True"):
            make_shardkv(bug=True)

    def test_shard_and_group_caps(self):
        with pytest.raises(ValueError, match="n_shards"):
            make_shardkv(n_shards=9)
        with pytest.raises(ValueError, match="n_groups"):
            make_shardkv(n_groups=16)


@pytest.mark.slow
class TestLargeFleet:
    def test_n17_campaign_halts_clean(self):
        # n = 2 + 5*3 = 17 nodes: the per-node (N, N) state surfaces
        # at a size no 5-node protocol core reaches
        box = {}

        def hinv(h):
            box["h"] = h
            return np.ones(len(h.count), bool)

        rep = search_seeds(
            make_shardkv(n_groups=5, record=True), _CFG, None,
            n_seeds=256, max_steps=8000, history_invariant=hinv,
        )
        assert rep.ok.all(), rep.failing_seeds
        assert rep.halted.all()
        assert _both(box["h"]).all()
