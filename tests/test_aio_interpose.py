"""Raw-asyncio interposition (runtime/aio.py): unmodified ``import
asyncio`` code runs deterministically inside the simulator.

The reference's madsim-tokio makes user code run unchanged by swapping
the runtime at build time (madsim-tokio/src/lib.rs); the Python analog
installs a sim-backed loop in asyncio's running-loop slot around every
poll. These tests drive the STDLIB's own primitives (no compat import
anywhere) through the sim and pin virtual-time behavior, determinism,
cancellation semantics, and non-interference with real asyncio.
"""

import asyncio
import os

import pytest

import madsim_tpu as ms
from madsim_tpu.runtime.builder import Builder


def run_sim(workload, seed=7):
    b = Builder()
    b.seed = seed
    b.count = 1
    # honor the determinism re-check tier (make determinism): every
    # raw-asyncio workload replays under the RNG-op-log checker too
    b.check_determinism = bool(os.environ.get("MADSIM_TEST_CHECK_DETERMINISM"))
    return b.run(workload)


def test_raw_sleep_rides_virtual_time():
    async def main():
        t0 = ms.now_ns()
        await asyncio.sleep(3.0)
        return ms.now_ns() - t0

    elapsed = run_sim(main)
    # virtual: exactly ~3 s (+poll epsilons), regardless of wall time
    assert 3_000_000_000 <= elapsed < 3_100_000_000


def test_raw_sleep_zero_yields():
    async def main():
        await asyncio.sleep(0)
        return "ok"

    assert run_sim(main) == "ok"


def test_raw_queue_event_gather():
    async def main():
        q = asyncio.Queue(maxsize=2)
        ev = asyncio.Event()

        async def producer():
            for i in range(5):
                await asyncio.sleep(0.01)
                await q.put(i)  # maxsize=2: exercises the putter-wait path
            ev.set()
            return "done"

        async def consumer():
            got = [await q.get() for _ in range(5)]
            await ev.wait()
            return got

        return await asyncio.gather(producer(), consumer())

    out = run_sim(main)
    assert out == ["done", [0, 1, 2, 3, 4]]


def test_raw_lock_semaphore_condition():
    async def main():
        lock = asyncio.Lock()
        sem = asyncio.Semaphore(2)
        cond = asyncio.Condition()
        order = []

        async def worker(i):
            async with sem:
                async with lock:
                    order.append(i)
                    await asyncio.sleep(0.01)

        async def waiter():
            async with cond:
                await cond.wait()
                return "notified"

        w = asyncio.create_task(waiter())
        await asyncio.gather(*(worker(i) for i in range(4)))
        await asyncio.sleep(0.01)
        async with cond:
            cond.notify_all()
        return sorted(order), await w

    order, note = run_sim(main)
    assert order == [0, 1, 2, 3]
    assert note == "notified"


def test_raw_timeout_and_wait_for():
    async def main():
        t0 = ms.now_ns()
        try:
            async with asyncio.timeout(0.05):
                await asyncio.sleep(100.0)
        except TimeoutError:
            pass
        else:  # pragma: no cover
            raise AssertionError("timeout did not fire")
        with pytest.raises(TimeoutError):
            await asyncio.wait_for(asyncio.sleep(100.0), timeout=0.05)
        # both timeouts burned ~0.1 s of VIRTUAL time, not 200 s
        return ms.now_ns() - t0

    elapsed = run_sim(main)
    assert 100_000_000 <= elapsed < 200_000_000


def test_raw_timeout_body_completes():
    async def main():
        async with asyncio.timeout(10.0):
            await asyncio.sleep(0.01)
        return "survived"

    assert run_sim(main) == "survived"


def test_raw_create_task_cancel():
    async def main():
        cancelled = []

        async def spin():
            try:
                await asyncio.sleep(1000.0)
            except asyncio.CancelledError:
                cancelled.append(True)
                raise

        t = asyncio.create_task(spin())
        await asyncio.sleep(0.01)
        assert not t.done()
        t.cancel()
        await asyncio.sleep(0.01)
        return t.cancelled(), cancelled

    was_cancelled, saw = run_sim(main)
    assert was_cancelled and saw == [True]


def test_raw_wait_and_shield():
    async def main():
        async def quick():
            await asyncio.sleep(0.01)
            return "q"

        async def slow():
            await asyncio.sleep(5.0)
            return "s"

        t1 = asyncio.create_task(quick())
        t2 = asyncio.create_task(slow())
        done, pending = await asyncio.wait(
            {t1, t2}, return_when=asyncio.FIRST_COMPLETED
        )
        assert t1 in done and t2 in pending
        # shield: the inner task survives the outer cancellation
        inner = asyncio.create_task(slow())
        with pytest.raises(TimeoutError):
            await asyncio.wait_for(asyncio.shield(inner), timeout=0.01)
        assert not inner.done()
        return await inner

    assert run_sim(main) == "s"


def test_raw_current_task_named():
    async def main():
        async def sub():
            return asyncio.current_task().get_name()

        t = asyncio.create_task(sub(), name="subtask")
        return asyncio.current_task() is not None, await t

    has_current, name = run_sim(main)
    assert has_current and name == "subtask"


def test_raw_asyncio_is_deterministic():
    async def main():
        q = asyncio.Queue()
        log = []

        async def node(i):
            await asyncio.sleep(0.001 * (i + 1))
            await q.put((i, ms.now_ns()))

        for i in range(8):
            asyncio.create_task(node(i))
        for _ in range(8):
            log.append(await q.get())
        return log

    a = run_sim(main, seed=11)
    b = run_sim(main, seed=11)
    c = run_sim(main, seed=12)
    assert a == b, "same seed must replay bit-identically"
    assert a != c, "different seed must schedule differently"


def test_raw_barrier():
    # asyncio.Barrier (3.11+): parties rendezvous on virtual time
    async def main():
        b = asyncio.Barrier(3)
        order = []

        async def party(i):
            await asyncio.sleep(0.01 * i)
            await b.wait()
            order.append((i, ms.now_ns()))

        async with asyncio.TaskGroup() as tg:
            for i in range(3):
                tg.create_task(party(i))
        return order

    order = run_sim(main)
    assert sorted(i for i, _t in order) == [0, 1, 2]
    # all three released at the same virtual instant window (after the
    # slowest arrival at ~0.02s)
    times = [t for _i, t in order]
    assert min(times) >= 20_000_000
    assert max(times) - min(times) < 1_000_000


def test_fuzzed_raw_asyncio_is_deterministic():
    """The race-detector analog for the interposition layer: a RANDOM
    program of raw-asyncio primitives (queues, sleeps, timeouts,
    cancels, TaskGroup, locks — all driven by the interposed seeded
    RNG) must replay bit-identically per seed. Catches any hidden
    nondeterminism in the loop implementation (address-ordered
    containers, GC-timing dependence, wall-clock leaks)."""
    import random as _random

    async def main():
        log = []
        q = asyncio.Queue(maxsize=3)
        lock = asyncio.Lock()

        async def actor(i):
            for step in range(6):
                op = _random.randrange(5)
                if op == 0:
                    await asyncio.sleep(_random.uniform(0.001, 0.05))
                elif op == 1:
                    try:
                        async with asyncio.timeout(_random.uniform(0.005, 0.05)):
                            await q.get()
                            log.append((i, step, "got"))
                    except TimeoutError:
                        log.append((i, step, "timeout"))
                elif op == 2:
                    try:
                        async with asyncio.timeout(0.05):
                            await q.put(_random.randrange(100))
                            log.append((i, step, "put"))
                    except TimeoutError:
                        log.append((i, step, "put-timeout"))
                elif op == 3:
                    async with lock:
                        await asyncio.sleep(0.002)
                        log.append((i, step, "locked", ms.now_ns()))
                else:
                    t = asyncio.create_task(asyncio.sleep(10.0))
                    await asyncio.sleep(0.001)
                    t.cancel()
                    log.append((i, step, "cancelled"))

        async with asyncio.TaskGroup() as tg:
            for i in range(5):
                tg.create_task(actor(i))
        log.append(("end", ms.now_ns()))
        return log

    for seed in (101, 202):
        a = run_sim(main, seed=seed)
        b = run_sim(main, seed=seed)
        assert a == b, f"seed {seed} did not replay identically"
    assert run_sim(main, seed=101) != run_sim(main, seed=202)


def test_raw_task_exception_routes_to_awaiter():
    # a task created via RAW asyncio.create_task carries asyncio
    # exception semantics: the exception is stored for the awaiter,
    # the sim itself keeps running (spawn/compat tasks keep the madsim
    # fail-the-sim semantics — test_runtime covers those)
    async def main():
        async def boom():
            await asyncio.sleep(0.01)
            raise ValueError("boom")

        t = asyncio.create_task(boom())
        await asyncio.sleep(0.1)  # sim survives the unawaited failure
        with pytest.raises(ValueError, match="boom"):
            await t
        return "sim-continued"

    assert run_sim(main) == "sim-continued"


def test_raw_gather_return_exceptions():
    async def main():
        async def bad():
            raise ValueError("x")

        async def good():
            await asyncio.sleep(0.01)
            return 1

        out = await asyncio.gather(bad(), good(), return_exceptions=True)
        return out

    out = run_sim(main)
    assert isinstance(out[0], ValueError) and out[1] == 1


def test_raw_cancel_can_be_suppressed():
    # asyncio.Task.cancel REQUESTS cancellation: a task that catches
    # CancelledError and returns still delivers its result
    async def main():
        async def stubborn():
            try:
                await asyncio.sleep(1000.0)
            except asyncio.CancelledError:
                return "survived"

        t = asyncio.create_task(stubborn())
        await asyncio.sleep(0.01)
        t.cancel()
        return await t

    assert run_sim(main) == "survived"


def test_raw_create_task_context_kwarg():
    import contextvars

    cv = contextvars.ContextVar("cv", default="outer")

    async def main():
        async def child():
            await asyncio.sleep(0.01)  # context must survive suspension
            return cv.get()

        ctx = contextvars.copy_context()
        ctx.run(cv.set, "inner")
        t = asyncio.create_task(child(), context=ctx)
        plain = asyncio.create_task(child())
        return await t, await plain

    assert run_sim(main) == ("inner", "outer")


def test_raw_create_task_isolates_context_by_default():
    # asyncio.Task copies the current context when context=None: a
    # child's contextvar mutations must not leak into the parent
    import contextvars

    cv = contextvars.ContextVar("cv2", default="outer")

    async def main():
        async def child():
            cv.set("leaked?")
            await asyncio.sleep(0.01)
            return cv.get()

        t = asyncio.create_task(child())
        inner = await t
        return inner, cv.get()

    assert run_sim(main) == ("leaked?", "outer")


def test_raw_to_thread_and_run_in_executor():
    import time as _time

    async def main():
        def blocking(x):
            _time.sleep(0.5)  # interposed: advances VIRTUAL time
            return x * 2

        t0 = ms.now_ns()
        r1 = await asyncio.to_thread(blocking, 21)
        r2 = await asyncio.get_running_loop().run_in_executor(
            None, blocking, 4
        )
        return r1, r2, ms.now_ns() - t0

    r1, r2, elapsed = run_sim(main)
    assert (r1, r2) == (42, 8)
    assert elapsed >= 1_000_000_000  # two simulated 0.5 s sleeps


def test_unknown_awaitable_still_rejected():
    class Weird:
        def __await__(self):
            yield object()

    async def main():
        await Weird()

    with pytest.raises(TypeError, match="non-simulation awaitable"):
        run_sim(main)


def test_real_asyncio_untouched_outside_sim():
    # the std backends run real loops between sims; the interposition
    # must not leak out of poll scopes
    async def real_main():
        await asyncio.sleep(0)
        q = asyncio.Queue()
        await q.put(1)
        return await q.get()

    assert asyncio.run(real_main()) == 1


def test_sim_inside_real_loop_restores_slot():
    # a sim run synchronously from inside a real asyncio coroutine must
    # restore the outer loop's running-loop slot (save/restore, not
    # reset-to-None)
    async def real_main():
        loop_before = asyncio.get_running_loop()

        async def sim_main():
            await asyncio.sleep(0.01)
            return "sim-done"

        assert run_sim(sim_main) == "sim-done"
        assert asyncio.get_running_loop() is loop_before
        return "ok"

    assert asyncio.run(real_main()) == "ok"


def test_raw_taskgroup():
    # asyncio.TaskGroup (3.11+): create_task via the group, implicit
    # join at __aexit__ — all through the interposed loop + task shim
    async def main():
        async def job(i):
            await asyncio.sleep(0.01 * (i + 1))
            return i * 10

        async with asyncio.TaskGroup() as tg:
            ts = [tg.create_task(job(i)) for i in range(4)]
        return [t.result() for t in ts]

    assert run_sim(main) == [0, 10, 20, 30]


def test_raw_taskgroup_failure_cancels_siblings():
    async def main():
        events = []

        async def boom():
            await asyncio.sleep(0.01)
            raise RuntimeError("tg-boom")

        async def slow():
            try:
                await asyncio.sleep(100.0)
            except asyncio.CancelledError:
                events.append("sibling-cancelled")
                raise

        try:
            async with asyncio.TaskGroup() as tg:
                tg.create_task(boom())
                tg.create_task(slow())
        except* RuntimeError:
            events.append("group-raised")
        return events

    out = run_sim(main)
    assert "sibling-cancelled" in out and "group-raised" in out


def test_raw_as_completed_orders_by_virtual_time():
    async def main():
        async def job(i):
            await asyncio.sleep(0.01 * (i + 1))
            return i

        results = []
        for fut in asyncio.as_completed([job(2), job(0), job(1)]):
            results.append(await fut)
        return results

    assert run_sim(main) == [0, 1, 2]


def test_raw_as_completed_timeout():
    # the sim's deterministic as_completed (runtime/aio.py — stdlib's
    # spawns in set order, which diverges on replay): remaining waiters
    # raise TimeoutError after the deadline, finished ones still yield
    async def main():
        async def job(d):
            await asyncio.sleep(d)
            return d

        got, timed_out = [], 0
        for fut in asyncio.as_completed([job(0.01), job(5.0)], timeout=0.1):
            try:
                got.append(await fut)
            except TimeoutError:
                timed_out += 1
        return got, timed_out

    got, timed_out = run_sim(main)
    assert got == [0.01] and timed_out == 1


def test_raw_wait_for_over_sim_native_awaitable():
    # stdlib wait_for wrapping a madsim-native awaitable: ensure_future
    # wraps the coroutine through the interposed loop's create_task
    async def main():
        with pytest.raises(TimeoutError):
            await asyncio.wait_for(ms.sleep(100.0), timeout=0.05)
        return "ok"

    assert run_sim(main) == "ok"


def test_raw_timeout_at_uses_loop_clock():
    async def main():
        t = asyncio.get_event_loop().time()
        with pytest.raises(TimeoutError):
            async with asyncio.timeout_at(t + 0.05):
                await asyncio.sleep(50.0)
        return ms.now_ns()

    # the deadline rode the VIRTUAL clock: ~0.05 s, not 50
    assert run_sim(main) < 1_000_000_000


def test_raw_asyncio_composes_with_service_shims():
    # the gRPC service sim driven through raw-asyncio constructs: a
    # TaskGroup of concurrent unary calls under asyncio.timeout — sim
    # futures (the service shim's internals) and asyncio futures mix
    # freely inside one coroutine tree
    from madsim_tpu.services import grpc

    class Greeter:
        SERVICE_NAME = "helloworld.Greeter"

        async def say_hello(self, request):
            return {"message": f"Hello {request.message['name']}!"}

    async def main():
        h = ms.Handle.current()

        async def serve():
            await grpc.Server.builder().add_service(Greeter()).serve(
                "0.0.0.0:50051"
            )

        h.create_node().name("grpc").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("client").ip("10.0.0.2").build()

        async def client():
            await asyncio.sleep(0.1)
            ch = await grpc.connect("10.0.0.1:50051")
            c = grpc.service_client(Greeter, ch)

            async def one(i):
                async with asyncio.timeout(5.0):
                    r = await c.say_hello({"name": f"n{i}"})
                    return r["message"]

            async with asyncio.TaskGroup() as tg:
                ts = [tg.create_task(one(i)) for i in range(4)]
            return sorted(t.result() for t in ts)

        return await cli.spawn(client())

    out = run_sim(main)
    assert out == [f"Hello n{i}!" for i in range(4)]


def test_raw_asyncio_with_chaos_kill():
    # raw-asyncio code on a killed node: its tasks die with the node
    async def main():
        h = ms.Handle.current()
        state = {"progress": 0}

        async def victim():
            while True:
                await asyncio.sleep(0.01)  # raw sleep on a sim node
                state["progress"] += 1

        node = h.create_node().name("victim").build()
        node.spawn(victim())
        await ms.sleep(0.1)
        h.kill(node.id)
        at_kill = state["progress"]
        await ms.sleep(0.1)
        return at_kill, state["progress"]

    at_kill, after = run_sim(main)
    assert at_kill > 0, "victim must have run before the kill"
    assert after == at_kill, "killed node's raw-asyncio task must stop"

    # mixed await styles in one coroutine: compat sleep + raw sleep
    async def mixed():
        t0 = ms.now_ns()
        await ms.sleep(0.05)
        await asyncio.sleep(0.05)
        return ms.now_ns() - t0

    assert run_sim(mixed) >= 100_000_000
