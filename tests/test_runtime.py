"""Core executor semantics, mirroring the reference's in-module test
strategy (SURVEY.md §4: madsim/src/sim/task.rs:727-954 and
sim/time/mod.rs:217-246)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.runtime.task import DeadlockError, JoinError, TimeLimitError


def test_block_on_returns_value():
    async def main():
        return 42

    assert ms.Runtime(seed=1).block_on(main()) == 42


def test_spawn_join_returns_value():
    async def child():
        await ms.sleep(1.0)
        return "done"

    async def main():
        jh = ms.spawn(child())
        return await jh

    assert ms.Runtime(seed=1).block_on(main()) == "done"


def test_sleep_ordering_and_clock():
    """Sleeps complete in deadline order and the virtual clock advances
    without real time passing (reference time/mod.rs:217-246)."""
    order = []

    async def sleeper(d, tag):
        await ms.sleep(d)
        order.append(tag)

    async def main():
        start = ms.now()
        for d, tag in [(3.0, "c"), (1.0, "a"), (2.0, "b")]:
            ms.spawn(sleeper(d, tag))
        await ms.sleep(4.0)
        assert 4.0 <= start.elapsed() < 4.1
        return order

    assert ms.Runtime(seed=7).block_on(main()) == ["a", "b", "c"]


def test_same_seed_identical_schedule():
    """Same seed => identical task interleaving (determinism invariant)."""

    def run(seed):
        order = []

        async def worker(i):
            order.append(i)

        async def main():
            for i in range(20):
                ms.spawn(worker(i))
            await ms.sleep(1.0)
            return tuple(order)

        return ms.Runtime(seed=seed).block_on(main())

    assert run(5) == run(5)


def test_different_seeds_different_schedules():
    """Random scheduling: different seeds explore different interleavings
    (reference task.rs:882-905)."""

    def run(seed):
        order = []

        async def worker(i):
            order.append(i)

        async def main():
            for i in range(20):
                ms.spawn(worker(i))
            await ms.sleep(1.0)
            return tuple(order)

        return ms.Runtime(seed=seed).block_on(main())

    schedules = {run(s) for s in range(10)}
    assert len(schedules) >= 2


def test_timeout_elapsed_and_success():
    async def main():
        # success path
        v = await ms.timeout(2.0, ms.sleep(1.0))
        assert v is None
        # timeout path
        with pytest.raises(ms.Elapsed):
            await ms.timeout(1.0, ms.sleep(10.0))
        return True

    assert ms.Runtime(seed=3).block_on(main())


def test_timeout_cancels_inner_coroutine():
    cleaned = []

    async def slow():
        try:
            await ms.sleep(100.0)
        finally:
            cleaned.append(True)

    async def main():
        with pytest.raises(ms.Elapsed):
            await ms.timeout(1.0, slow())
        return True

    assert ms.Runtime(seed=3).block_on(main())
    assert cleaned == [True]


def test_interval_ticks():
    async def main():
        ticks = []
        it = ms.interval(1.0)
        for _ in range(3):
            t = await it.tick()
            ticks.append(t.ns)
        return ticks

    ticks = ms.Runtime(seed=9).block_on(main())
    assert len(ticks) == 3
    # ~1s apart (modulo poll-cost jitter)
    assert 0.9e9 < ticks[1] - ticks[0] < 1.1e9
    assert 0.9e9 < ticks[2] - ticks[1] < 1.1e9


def test_kill_drops_futures():
    """Kill cancels tasks so their cleanup runs — the analog of
    kill-drops-futures (reference task.rs:934-953)."""
    cleaned = []

    async def victim():
        try:
            await ms.sleep(1000.0)
        finally:
            cleaned.append("cleanup-ran")

    async def main():
        h = ms.Handle.current()
        node = h.create_node().name("victim-node").build()
        node.spawn(victim())
        await ms.sleep(1.0)
        h.kill(node)
        await ms.sleep(1.0)
        return list(cleaned)

    assert ms.Runtime(seed=11).block_on(main()) == ["cleanup-ran"]


def test_await_killed_task_raises_join_error():
    async def victim():
        await ms.sleep(1000.0)

    async def main():
        h = ms.Handle.current()
        node = h.create_node().build()
        jh = node.spawn(victim())
        await ms.sleep(1.0)
        h.kill(node)
        try:
            await jh
        except JoinError:
            return "join-error"
        return "no-error"

    assert ms.Runtime(seed=11).block_on(main()) == "join-error"


def test_restart_replays_init():
    """Restart re-runs the stored init task (reference task.rs:279-291)."""
    starts = []

    async def main():
        h = ms.Handle.current()

        async def init():
            starts.append(ms.now_ns())

        node = h.create_node().init(init).build()
        await ms.sleep(1.0)
        h.restart(node)
        await ms.sleep(1.0)
        return len(starts)

    assert ms.Runtime(seed=2).block_on(main()) == 2


def test_restart_on_panic():
    """A panicking task on a restart_on_panic node restarts the node after
    a random 1-10 s delay (reference task.rs:187-206)."""
    attempts = {"n": 0}

    async def main():
        h = ms.Handle.current()
        done = ms.SimFuture()

        async def init():
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise RuntimeError("boom")
            done.set_result(ms.now_ns())

        h.create_node().init(init).restart_on_panic().build()
        t = await done
        return t

    t_done = ms.Runtime(seed=4).block_on(main())
    assert attempts["n"] == 2
    assert t_done >= 1_000_000_000  # restart came >= 1s later


def test_pause_resume():
    progress = []

    async def worker():
        for i in range(10):
            progress.append(i)
            await ms.sleep(1.0)

    async def main():
        h = ms.Handle.current()
        node = h.create_node().build()
        node.spawn(worker())
        await ms.sleep(2.5)
        h.pause(node)
        n_at_pause = len(progress)
        await ms.sleep(3.0)
        assert len(progress) == n_at_pause  # frozen while paused
        h.resume(node)
        await ms.sleep(3.0)
        assert len(progress) > n_at_pause  # resumed
        return True

    assert ms.Runtime(seed=13).block_on(main())


def test_unhandled_panic_fails_simulation():
    async def bad():
        raise ValueError("kaboom")

    async def main():
        ms.spawn(bad())
        await ms.sleep(1.0)

    with pytest.raises(ValueError, match="kaboom"):
        ms.Runtime(seed=1).block_on(main())


def test_deadlock_detection():
    async def main():
        await ms.SimFuture()  # never resolved, no timers

    with pytest.raises(DeadlockError):
        ms.Runtime(seed=1).block_on(main())


def test_time_limit():
    async def main():
        await ms.sleep(100.0)

    rt = ms.Runtime(seed=1)
    rt.set_time_limit(1.0)
    with pytest.raises(TimeLimitError):
        rt.block_on(main())


def test_select_and_join_all():
    async def main():
        a, b = ms.sleep(2.0), ms.sleep(1.0)
        idx, _ = await ms.select(a, b)
        assert idx == 1

        async def val(x):
            await ms.sleep(0.1)
            return x

        # JoinHandles directly (tokio join_all-over-handles parity) …
        r = await ms.join_all([ms.spawn(val(i)) for i in range(5)])
        assert r == [0, 1, 2, 3, 4]
        # … and select over a handle/future mix
        idx2, _ = await ms.select(ms.spawn(val("slowish")), ms.sleep(0.01))
        assert idx2 == 1
        return True

    assert ms.Runtime(seed=6).block_on(main())


def test_get_node_and_name_resolution():
    """ToNodeId analog: chaos APIs take ids, handles, or names
    (task.rs:366-397), and get_node looks nodes up (mod.rs:271)."""
    async def main():
        h = ms.Handle.current()
        n = h.create_node().name("worker-a").ip("10.0.0.5").build()
        assert h.get_node("worker-a").id == n.id
        assert h.get_node(n.id).name == "worker-a"
        assert h.get_node(n).ip == "10.0.0.5"
        assert h.get_node("absent") is None
        ticks = []

        async def loop():
            while True:
                await ms.sleep(0.1)
                ticks.append(ms.now_ns())

        n.spawn(loop())
        await ms.sleep(0.55)
        h.pause("worker-a")          # chaos by name
        frozen = len(ticks)
        await ms.sleep(0.5)
        assert len(ticks) == frozen
        h.resume("worker-a")
        await ms.sleep(0.5)
        assert len(ticks) > frozen
        try:
            h.kill("absent")
        except LookupError:
            return True
        raise AssertionError("kill of unknown name must raise")

    assert ms.Runtime(seed=9).block_on(main())


def test_check_determinism_passes_for_deterministic_workload():
    async def wl():
        for _ in range(5):
            ms.thread_rng().random_float()
            await ms.sleep(0.5)
        return "ok"

    assert ms.Runtime.check_determinism(seed=17, workload=wl) == "ok"


def test_check_determinism_catches_nondeterminism():
    """Hidden external state changes behavior between runs => the replay
    diverges (reference rand.rs:77-85 'non-determinism detected')."""
    state = {"runs": 0}

    async def wl():
        state["runs"] += 1
        await ms.sleep(float(state["runs"]))  # different timing per run
        ms.thread_rng().random_float()

    with pytest.raises(ms.DeterminismError):
        ms.Runtime.check_determinism(seed=17, workload=wl)


def test_base_time_randomized_per_seed():
    def base(seed):
        async def main():
            return ms.SystemTime.now().timestamp()

        return ms.Runtime(seed=seed).block_on(main())

    t1, t2 = base(1), base(2)
    assert t1 != t2
    # within calendar year 2022 (reference time/mod.rs:26-37)
    assert 1_640_995_200 <= t1 <= 1_672_531_200


def test_spawn_returns_value_nested():
    async def inner():
        return 7

    async def outer():
        return await ms.spawn(inner()) + 1

    async def main():
        return await ms.spawn(outer())

    assert ms.Runtime(seed=1).block_on(main()) == 8


def test_restart_on_panic_kills_siblings_immediately():
    """Reference task.rs:199-205: the node is killed at panic time; sibling
    tasks must stop before the delayed restart."""
    sibling_progress = []

    async def main():
        h = ms.Handle.current()
        done = ms.SimFuture()
        state = {"n": 0}

        async def init():
            state["n"] += 1
            if state["n"] == 2:
                done.set_result(None)
                return

            async def sibling():
                while True:
                    sibling_progress.append(ms.now_ns())
                    await ms.sleep(0.1)

            ms.spawn(sibling())
            await ms.sleep(0.5)
            raise RuntimeError("crash")

        h.create_node().init(init).restart_on_panic().build()
        await done
        # sibling must have stopped at panic time (~0.5s), not kept running
        # into the 1-10s restart delay
        return max(sibling_progress)

    last_beat = ms.Runtime(seed=8).block_on(main())
    assert last_beat < 700_000_000  # stopped around the 0.5s crash


def test_panic_fails_simulation_even_if_awaited_later():
    """Error routing must not depend on scheduling order: a panic always
    fails the sim (reference: unwind propagates through block_on)."""

    async def bad():
        raise ValueError("early-crash")

    async def main():
        jh = ms.spawn(bad())
        await ms.sleep(1.0)  # panic happens during this sleep
        try:
            await jh
        except Exception:
            return "caught"

    with pytest.raises(ValueError, match="early-crash"):
        ms.Runtime(seed=1).block_on(main())


def test_self_kill_runs_cleanup():
    """A task killing its own node still gets its finally blocks run at the
    next suspension point (drop semantics, task.rs:270-271)."""
    cleaned = []

    async def main():
        h = ms.Handle.current()
        node = h.create_node().build()

        async def suicidal():
            try:
                h.kill(node)
                await ms.sleep(10.0)  # never completes
                cleaned.append("not-reached")
            finally:
                cleaned.append("cleanup")

        node.spawn(suicidal())
        await ms.sleep(1.0)
        return list(cleaned)

    assert ms.Runtime(seed=5).block_on(main()) == ["cleanup"]


def test_check_determinism_with_unhashable_draws():
    import random as stdlib_random

    async def wl():
        return stdlib_random.choice([[1], [2], [3]])

    # must not crash on hash([1]) while logging draws
    assert ms.Runtime.check_determinism(seed=9, workload=wl) in ([1], [2], [3])


def test_yield_now_and_spawn_blocking():
    """yield_now reschedules without advancing the clock past the tick
    (tokio task::yield_now re-export); spawn_blocking runs a sync
    closure in a task (task.rs:498-511)."""
    async def main():
        t0 = ms.now_ns()
        order = []

        async def other():
            order.append("other")

        ms.spawn(other())
        await ms.yield_now()
        order.append("self")
        assert order == ["other", "self"]
        assert ms.now_ns() - t0 < 1_000_000  # poll costs only, no sleep
        h = ms.spawn_blocking(lambda: 6 * 7)
        assert await h == 42
        return True

    assert ms.Runtime(seed=4).block_on(main())


def test_cancel_on_drop_scope():
    """cancel_on_drop: the task is aborted when the scope exits with it
    still running (the JoinHandle drop analog, task.rs:581-616)."""
    cleaned = []

    async def victim():
        try:
            await ms.sleep(1000.0)
        finally:
            cleaned.append("cleanup")

    async def quick():
        await ms.sleep(0.1)
        return "done"

    async def main():
        async with ms.spawn(victim()).cancel_on_drop():
            await ms.sleep(1.0)
        await ms.sleep(0.5)
        assert cleaned == ["cleanup"]
        # a finished task is left alone (and awaitable through the scope)
        ft = ms.spawn(quick()).cancel_on_drop()
        async with ft as h:
            assert await h == "done"
        return True

    assert ms.Runtime(seed=17).block_on(main())


def test_join_error_is_cancelled_vs_is_panic():
    """JoinError accessors mirror the reference (task.rs:620-631)."""
    async def main():
        h = ms.Handle.current()
        node = h.create_node().build()
        async def sleeper():
            await ms.sleep(1000.0)

        jh = node.spawn(sleeper())
        await ms.sleep(0.1)
        h.kill(node)
        try:
            await jh
            raise AssertionError("killed task must raise JoinError")
        except JoinError as e:
            assert e.is_cancelled() and not e.is_panic()
        return True

    assert ms.Runtime(seed=19).block_on(main())


def test_join_error_is_panic_on_restart_on_panic_node():
    """A raised exception on a restart_on_panic node surfaces to the
    JoinHandle as a panic JoinError (task.rs:620-631 accessors; the
    cancelled branch is covered by the kill test above)."""
    async def main():
        h = ms.Handle.current()
        node = h.create_node().restart_on_panic().build()

        async def boom():
            raise ValueError("kaboom")

        jh = node.spawn(boom())
        await ms.sleep(0.1)
        try:
            await jh
            raise AssertionError("panicked task must raise JoinError")
        except JoinError as e:
            assert e.is_panic() and not e.is_cancelled()
            assert isinstance(e.__cause__, ValueError)
        return True

    assert ms.Runtime(seed=23).block_on(main())
