"""The fuzzing farm (madsim_tpu/farm/) — pipelined generations,
multi-tenant scheduling, adaptive energy.

Pins, per the round's contract: the pipelined driver is bit-identical
to blocking ``run_device`` (corpus, coverage, violations, checkpoints)
while emitting the ``queue_wall_s``/``idle_wall_s`` split with
``host_syncs`` still 1/generation; a farm-scheduled tenant equals its
standalone run across preemption splices, with every generation
program traced exactly once for the whole session
(profiler-certified); the ``_GEN_CACHE`` LRU honors
``MADSIM_GEN_CACHE_MAX``, counts evictions, and an evicted program
re-traces without changing results; energy off/uniform is
bit-identical to the historical schedule and adaptive energy is
deterministic. Soak-scale certificates (the >= 1.25x gens/s A/B, the
3-tenant session, the adaptive-vs-uniform planted-bug hunt) live in
tools/farm_soak.py (FARM_r11.txt)."""

import json
import sys
from pathlib import Path

import pytest

from madsim_tpu import explore, farm, obs
from madsim_tpu.chaos import FaultPlan, GrayFailure, PauseStorm
from madsim_tpu.engine import EngineConfig
from madsim_tpu.explore import device as _device
from madsim_tpu.farm import EnergySchedule, FarmEnergy, Tenant
from madsim_tpu.models import make_raft
from madsim_tpu.obs import prof

NODES = (0, 1, 2, 3, 4)
CFG = EngineConfig(pool_size=64, loss_p=0.02)
PLAN = FaultPlan((
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="farm-test")


def _halt_inv(view):
    return view["halted"]


def _biased_inv(view):
    # deterministic pure-function-of-final-state "bug" (the
    # test_explore_device recipe): low-trace-hash seeds violate
    return (view["trace"] & 7) != 0


# ONE workload + invariant identity across the module (program caches
# key on identity); ONE campaign shape for most tests so the whole
# file shares two compiled programs
WL = make_raft()
KW = dict(generations=3, batch=16, root_seed=11, max_steps=200,
          cov_words=8, invariant=_halt_inv)


def _fp(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits, e.violating) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


# lazily computed shared campaigns (tier-1 wall is a budgeted resource)
_SHARED: dict = {}


def _rep_blocking():
    if "blocking" not in _SHARED:
        _SHARED["blocking"] = explore.run_device(WL, CFG, PLAN, **KW)
    return _SHARED["blocking"]


def _rep_pipelined():
    if "pipelined" not in _SHARED:
        records = []
        _SHARED["pipelined"] = farm.run_pipelined(
            WL, CFG, PLAN, telemetry=records.append, **KW
        )
        _SHARED["pipelined-records"] = records
    return _SHARED["pipelined"]


# ---------------------------------------------------------------------------
# pipelined generations
# ---------------------------------------------------------------------------


def test_pipelined_matches_blocking_bit_identical():
    assert _fp(_rep_blocking()) == _fp(_rep_pipelined())


def test_pipelined_wall_split_schema():
    _rep_pipelined()
    recs = _SHARED["pipelined-records"]
    gens = [r for r in recs if r.get("event") == "generation"]
    assert len(gens) == KW["generations"]
    for g in gens:
        # full device wall split, plus the pipeline's queue/idle view;
        # the ONE consume-point sync per generation is the design
        for k in ("dispatch_wall_s", "compile_wall_s", "sync_wall_s",
                  "queue_wall_s", "idle_wall_s"):
            assert k in g, f"missing {k}"
        assert g["host_syncs"] == 1
        assert g["dispatch_wall_s"] == pytest.approx(
            g["queue_wall_s"] + g["idle_wall_s"], abs=2e-3
        )
    end = next(r for r in recs if r.get("event") == "campaign_end")
    assert {"wall_queue_s", "wall_idle_s", "respeculations"} <= set(end)
    assert end["host_syncs"] == KW["generations"]
    # raft admits from generation 0, so breed speculation never misses
    assert end["respeculations"] == 0
    start = next(r for r in recs if r.get("event") == "campaign_start")
    assert start["driver"] == "device-pipelined"
    assert start["pipeline_depth"] == 2
    rep = _SHARED["pipelined"]
    assert rep.wall_dispatch_s == pytest.approx(
        rep.wall_queue_s + rep.wall_idle_s, abs=1e-6
    )
    assert "pipeline:" in rep.banner()
    # blocking reports render no pipeline line (zeros stay silent)
    assert "pipeline:" not in _rep_blocking().banner()


def test_pipelined_checkpoint_resume_splice(tmp_path):
    # the per-generation checkpoint must snapshot the campaign AS OF
    # that generation (not the speculative head): resume from a
    # pipelined checkpoint and land exactly on the uninterrupted run
    path = tmp_path / "pipe.ckpt"
    farm.run_pipelined(
        WL, CFG, PLAN, **{**KW, "generations": 2},
        checkpoint_path=str(path),
    )
    resumed = farm.run_pipelined(
        WL, CFG, PLAN, **{**KW, "generations": 1}, resume=str(path),
    )
    assert _fp(resumed) == _fp(_rep_blocking())


def test_pipelined_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        farm.run_pipelined(WL, CFG, PLAN, depth=0, **KW)


# ---------------------------------------------------------------------------
# the farm scheduler
# ---------------------------------------------------------------------------


def test_farm_two_tenant_preemption_bit_identity():
    # different campaign shapes (batch) -> distinct program cache keys:
    # the retrace pin below certifies tenant SWITCHING is compile-free
    kw2 = dict(invariant=_biased_inv, batch=24, root_seed=5,
               max_steps=200, cov_words=8)
    _device._GEN_CACHE.clear()
    with prof.profiled() as p:
        ref_a = explore.run_device(WL, CFG, PLAN, **KW)
        ref_b = explore.run_device(WL, CFG, PLAN, generations=3, **kw2)
        records = []
        fr = farm.run_farm(
            [
                Tenant("halt", WL, CFG, PLAN, generations=3,
                       kwargs={k: v for k, v in KW.items()
                               if k != "generations"}),
                Tenant("biased", WL, CFG, PLAN, generations=3,
                       kwargs=kw2),
            ],
            quantum=1, telemetry=records.append,
        )
    # preemption = the checkpoint/resume splice: scheduled == standalone
    assert _fp(fr.reports["halt"]) == _fp(ref_a)
    assert _fp(fr.reports["biased"]) == _fp(ref_b)
    # round-robin in declaration order, one-generation quanta
    assert fr.schedule == [
        (0, "halt", 1), (1, "biased", 1), (2, "halt", 1),
        (3, "biased", 1), (4, "halt", 1), (5, "biased", 1),
    ]
    assert fr.preemptions == {"halt": 2, "biased": 2}
    # every program traced EXACTLY once across standalone + 6 slices
    retr = p.retraces("explore.device")
    assert retr and all(v == 1 for v in retr.values())
    # every slice record carries its tenant tag
    gens = [r for r in records if r.get("event") == "generation"]
    assert len(gens) == 6
    assert {g["tenant"] for g in gens} == {"halt", "biased"}
    assert "2 tenants over 6 slices" in fr.banner()


def test_farm_total_generations_budget():
    fr = farm.run_farm(
        [Tenant("only", WL, CFG, PLAN, generations=None,
                kwargs={k: v for k, v in KW.items()
                        if k != "generations"})],
        quantum=2, total_generations=3,
    )
    # the farm budget bounds an unbounded tenant, last slice truncated
    assert [g for _, _, g in fr.schedule] == [2, 1]
    assert fr.reports["only"].generations == 3
    assert _fp(fr.reports["only"]) == _fp(_rep_blocking())


def test_farm_validation():
    t = Tenant("a", WL, CFG, PLAN, generations=1, kwargs={})
    with pytest.raises(ValueError, match="at least one"):
        farm.run_farm([])
    with pytest.raises(ValueError, match="unique"):
        farm.run_farm([t, Tenant("a", WL, CFG, PLAN, generations=1)])
    with pytest.raises(ValueError, match="quantum"):
        farm.run_farm([t], quantum=0)
    with pytest.raises(ValueError, match="budget"):
        farm.run_farm([Tenant("b", WL, CFG, PLAN)])
    with pytest.raises(ValueError, match="scheduler owns"):
        farm.run_farm([Tenant("c", WL, CFG, PLAN, generations=1,
                              kwargs={"resume": None})])


# ---------------------------------------------------------------------------
# energy
# ---------------------------------------------------------------------------


def test_energy_off_bit_identity_host():
    # the reproducible default: energy absent / None / uniform all run
    # the historical frontier-first schedule bit-identically
    kw = dict(generations=3, batch=16, root_seed=11, max_steps=200,
              cov_words=8, invariant=_biased_inv)
    base = explore.run(WL, CFG, PLAN, **kw)
    off = explore.run(WL, CFG, PLAN, energy=None, **kw)
    uni = explore.run(
        WL, CFG, PLAN, energy=EnergySchedule(mode="uniform"), **kw
    )
    assert _fp(base) == _fp(off) == _fp(uni)
    # the adaptive schedule is deterministic (integer weights, threefry
    # draws on the farm lane) and leaves per-seed semantics intact
    fast1 = explore.run(WL, CFG, PLAN, energy=EnergySchedule(), **kw)
    fast2 = explore.run(WL, CFG, PLAN, energy=EnergySchedule(), **kw)
    assert _fp(fast1) == _fp(fast2)


def test_energy_mode_validation():
    with pytest.raises(ValueError, match="energy mode"):
        EnergySchedule(mode="bogus").state()


def test_energy_weights_decay_and_boost():
    import numpy as np

    class _E:
        def __init__(self, id, new_bits, violating, cov):
            self.id, self.new_bits, self.violating = id, new_bits, violating
            self.cov = np.asarray(cov, np.uint32)

    # entry 1 violates and owns a rare bit; entry 0 is a plain seed
    corpus = [
        _E(0, 2, False, [0b11, 0]),
        _E(1, 4, True, [0b01, 0b1000]),
    ]
    st = EnergySchedule(rare_k=1).state()
    pool, cum = st.pool(corpus)
    # frontier order: violating first — entry 1 leads the pool
    assert [e.id for e in pool] == [1, 0]
    w = dict(zip((e.id for e in pool),
                 np.diff(np.concatenate([[0], cum]))))
    assert w[1] > w[0]  # violation + rare-path bonuses
    # picking an entry decays its weight next generation
    st.picks[1] = 8
    pool2, cum2 = st.pool(corpus)
    w2 = dict(zip((e.id for e in pool2),
                  np.diff(np.concatenate([[0], cum2]))))
    assert w2[1] < w[1] and w2[0] == w[0]
    assert all(x >= 1 for x in w2.values())  # the floor: nothing starves
    # the pool respects the frontier depth knob
    assert len(EnergySchedule(top=1).state().pool(corpus)[0]) == 1
    # inherit: None defers to the campaign's p; violating floors at 0.9
    from madsim_tpu.explore.mutate import inherit_threshold
    assert st.inherit_threshold(corpus[0], 0.8) == inherit_threshold(0.8)
    assert st.inherit_threshold(corpus[1], 0.8) == inherit_threshold(0.9)
    assert (EnergySchedule(inherit_seed_p=0.5)
            .state().inherit_threshold(corpus[0], 0.8)
            == inherit_threshold(0.5))


def test_farm_energy_pick_deterministic_and_bootstrapped():
    e = FarmEnergy(root_seed=7)
    names = ["a", "b", "c"]
    # never-run tenants draw at bootstrap weight; same inputs, same pick
    p0 = e.pick(0, names, {})
    assert p0 == e.pick(0, names, {}) and p0 in names
    # a tenant still finding things dominates two plateaued ones
    gains = {"a": (0, 0), "b": (40, 2), "c": (0, 0)}
    picks = {e.pick(i, names, gains) for i in range(16)}
    assert "b" in picks
    assert sum(e.pick(i, names, gains) == "b" for i in range(32)) > 16
    # uniform mode is inert: run_farm falls back to round-robin
    assert not FarmEnergy(mode="uniform").active


# ---------------------------------------------------------------------------
# flight tagging + the farm dashboard
# ---------------------------------------------------------------------------


def _tools():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    try:
        import campaign_top
    finally:
        sys.path.pop(0)
    return campaign_top


def test_flight_recorder_tagged_streams():
    records = []
    fr = obs.FlightRecorder(records.append, heartbeat_s=0.0,
                            profile=False, memory=False)
    a, b = fr.tagged("a"), fr.tagged("b")
    for sink, g in ((a, 0), (b, 0), (a, 1)):
        sink({"event": "campaign_start", "generations": 2})
        sink({"event": "generation", "generation": g, "cov_bits": 1 + g,
              "corpus_size": 1, "violations": 0})
    fr.close()
    gens = [r for r in records if r["event"] == "generation"]
    assert [g["tenant"] for g in gens] == ["a", "b", "a"]
    # ONE monotone seq/t_s spine across all tenants
    assert [r["seq"] for r in records] == list(range(len(records)))
    # heartbeats inherit the tenant of the generation they follow
    hbs = [r for r in records if r["event"] == "heartbeat"]
    assert [h["tenant"] for h in hbs] == ["a", "b", "a"]


def test_flight_summary_carries_gen_cache():
    records = []
    fr = obs.FlightRecorder(records.append, heartbeat_s=1e9,
                            profile=False, memory=False)
    fr({"event": "campaign_start", "generations": 1})
    fr({"event": "campaign_end"})
    fr.close()
    summary = next(r for r in records if r["event"] == "flight_summary")
    # explore.device is imported by this module: stats must be present
    assert summary["gen_cache"]["max"] >= 1
    assert summary["gen_cache"]["evictions"] >= 0


def test_campaign_top_farm_dashboard(tmp_path):
    campaign_top = _tools()
    path = tmp_path / "farm.jsonl"
    recs = [
        {"event": "campaign_start", "generations": 2, "tenant": "halt"},
        {"event": "generation", "generation": 0, "cov_bits": 40,
         "corpus_size": 9, "violations": 0, "dispatch_wall_s": 0.2,
         "sync_wall_s": 0.1, "tenant": "halt"},
        {"event": "generation", "generation": 0, "cov_bits": 30,
         "corpus_size": 7, "violations": 3, "dispatch_wall_s": 0.4,
         "tenant": "biased"},
        {"event": "campaign_end", "tenant": "halt"},
        {"event": "flight_summary",
         "gen_cache": {"entries": 4, "max": 8, "evictions": 1}},
    ]
    with open(path, "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")
        fh.write('{"event": "generation", "torn')  # crashed mid-write
    groups = campaign_top.group_streams([str(path)])
    assert [g[0] for g in groups] == ["halt", "biased", "(farm)"]
    frame = campaign_top.render_farm(groups)
    assert "halt" in frame and "biased" in frame
    assert "gen cache 4/8" in frame and "1 evictions" in frame
    # an untagged stream stays on the single-campaign dashboard
    single = tmp_path / "single.jsonl"
    single.write_text(json.dumps({"event": "generation", "cov_bits": 1,
                                  "generation": 0}) + "\n")
    groups1 = campaign_top.group_streams([str(single)])
    assert len(groups1) == 1 and groups1[0][1][0]["cov_bits"] == 1


# ---------------------------------------------------------------------------
# the generation-program cache LRU (run LAST: it evicts the module's
# warm programs)
# ---------------------------------------------------------------------------


def test_gen_cache_eviction_and_retrace(monkeypatch):
    monkeypatch.setenv("MADSIM_GEN_CACHE_MAX", "1")
    _device._GEN_CACHE.clear()
    kw = dict(generations=1, batch=16, root_seed=3, max_steps=200,
              cov_words=8, invariant=_halt_inv)
    with prof.profiled() as p:
        r1 = explore.run_device(WL, CFG, PLAN, **kw)
        s1 = _device.gen_cache_stats()
        # a second shape evicts the first (capacity 1)...
        explore.run_device(WL, CFG, PLAN, **{**kw, "batch": 24})
        s2 = _device.gen_cache_stats()
        # ...so the first re-traces on return — bit-identically
        r3 = explore.run_device(WL, CFG, PLAN, **kw)
        s3 = _device.gen_cache_stats()
    assert s1 == {"entries": 1, "max": 1, "evictions": s1["evictions"]}
    assert s2["entries"] == 1
    assert s3["evictions"] == s1["evictions"] + 2
    assert _fp(r1) == _fp(r3)
    retr = p.retraces("explore.device")
    # generations=1 never breeds: uniform-only, built twice for the
    # evicted shape, once for the evicting one
    assert sorted(retr.values()) == [1, 2]
    with pytest.raises(ValueError, match="MADSIM_GEN_CACHE_MAX"):
        monkeypatch.setenv("MADSIM_GEN_CACHE_MAX", "zero")
        _device._gen_cache_max()


# ---------------------------------------------------------------------------
# the full matrix (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_farm_three_tenant_full_matrix():
    kws = {
        "halt": dict(invariant=_halt_inv, batch=16, root_seed=11,
                     max_steps=200, cov_words=8),
        "biased": dict(invariant=_biased_inv, batch=24, root_seed=5,
                       max_steps=200, cov_words=8),
        "wide": dict(invariant=_halt_inv, batch=32, root_seed=2,
                     max_steps=300, cov_words=16),
    }
    refs = {
        name: explore.run_device(WL, CFG, PLAN, generations=4, **kw)
        for name, kw in kws.items()
    }
    for quantum in (1, 2):
        for pipeline in (False, True):
            fr = farm.run_farm(
                [Tenant(n, WL, CFG, PLAN, generations=4, kwargs=kw)
                 for n, kw in kws.items()],
                quantum=quantum, pipeline=pipeline,
            )
            for name, ref in refs.items():
                assert _fp(fr.reports[name]) == _fp(ref), (
                    f"{name} diverged at quantum={quantum} "
                    f"pipeline={pipeline}"
                )
    # adaptive tenant energy at an equal farm budget still terminates
    # with every tenant's campaign bit-identical to standalone
    fr = farm.run_farm(
        [Tenant(n, WL, CFG, PLAN, generations=4, kwargs=kw)
         for n, kw in kws.items()],
        quantum=1, energy=FarmEnergy(root_seed=7),
    )
    for name, ref in refs.items():
        assert _fp(fr.reports[name]) == _fp(ref)
