"""Tests for the batched JAX engine (madsim_tpu.engine).

The determinism invariants mirror the reference's test strategy
(SURVEY.md §4): same seed => identical trace, different seeds =>
different schedules, chaos semantics (kill drops in-flight events,
restart re-runs init, clog delays until unclog), plus the batched-core
specific invariants: batch result == per-seed results (vmap semantics),
jit == eager, and the jnp/numpy threefry mirrors agree bit-for-bit.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from madsim_tpu.engine import (
    KIND_KILL,
    KIND_RESTART,
    KIND_CLOG,
    KIND_UNCLOG,
    EngineConfig,
    Workload,
    make_init,
    make_run,
    make_step,
    np_threefry2x32,
    threefry2x32,
    user_kind,
)
from madsim_tpu.models import (
    make_broadcast,
    make_microbench,
    make_pingpong,
    make_raft,
)


def run_workload(wl, cfg, seeds, n_steps):
    init = make_init(wl, cfg)
    run = jax.jit(make_run(wl, cfg, n_steps))
    return run(init(np.asarray(seeds, np.uint64)))


# ---------------------------------------------------------------------------
# RNG
# ---------------------------------------------------------------------------


class TestThreefry:
    def test_jnp_matches_numpy_mirror(self):
        rng = np.random.RandomState(0)
        for _ in range(50):
            k0, k1, x0, x1 = rng.randint(0, 2**32, size=4, dtype=np.uint32)
            ja, jb = threefry2x32(k0, k1, x0, x1)
            na, nb = np_threefry2x32(k0, k1, x0, x1)
            assert np.uint32(ja) == na
            assert np.uint32(jb) == nb

    def test_known_distinctness(self):
        # different counters / keys give different outputs
        a, _ = threefry2x32(1, 2, 3, 4)
        b, _ = threefry2x32(1, 2, 3, 5)
        c, _ = threefry2x32(1, 3, 3, 4)
        assert int(a) != int(b) != int(c)

    def test_vmaps(self):
        xs = jnp.arange(16, dtype=jnp.uint32)
        outs, _ = jax.vmap(lambda x: threefry2x32(1, 2, x, 0))(xs)
        assert len(set(np.asarray(outs).tolist())) == 16


# ---------------------------------------------------------------------------
# Determinism invariants (the analog of check_determinism,
# reference runtime/mod.rs:165-190)
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_same_seed_identical_trace(self):
        wl = make_pingpong(rounds=5)
        cfg = EngineConfig(pool_size=64)
        a = run_workload(wl, cfg, np.arange(8), 200)
        b = run_workload(wl, cfg, np.arange(8), 200)
        assert np.array_equal(np.asarray(a.trace), np.asarray(b.trace))
        assert np.array_equal(np.asarray(a.now), np.asarray(b.now))

    def test_different_seeds_different_schedules(self):
        wl = make_pingpong(rounds=5)
        cfg = EngineConfig(pool_size=64)
        out = run_workload(wl, cfg, np.arange(16), 200)
        traces = np.asarray(out.trace)
        assert len(set(traces.tolist())) == 16

    def test_batch_equals_single(self):
        # vmap semantics: each row of a batched run must equal its own
        # single-seed run — seeds are fully independent
        wl = make_pingpong(rounds=3)
        cfg = EngineConfig(pool_size=64)
        batched = run_workload(wl, cfg, np.arange(6), 150)
        for s in range(6):
            single = run_workload(wl, cfg, [s], 150)
            assert int(single.trace[0]) == int(batched.trace[s])
            assert int(single.now[0]) == int(batched.now[s])

    def test_jit_equals_eager(self):
        wl = make_microbench(rounds=50)
        cfg = EngineConfig(pool_size=16)
        init = make_init(wl, cfg)
        st = init(np.arange(4, dtype=np.uint64))
        run = make_run(wl, cfg, 60)
        eager = run(st)
        jitted = jax.jit(run)(st)
        assert np.array_equal(np.asarray(eager.trace), np.asarray(jitted.trace))

    def test_trace_depends_on_config(self):
        wl = make_pingpong(rounds=3)
        a = run_workload(wl, EngineConfig(pool_size=64), [7], 150)
        b = run_workload(
            wl, EngineConfig(pool_size=64, lat_min_ns=100, lat_max_ns=200), [7], 150
        )
        assert int(a.trace[0]) != int(b.trace[0])

    def test_config_hash_stable(self):
        assert EngineConfig().hash() == EngineConfig().hash()
        assert EngineConfig().hash() != EngineConfig(loss_p=0.1).hash()


# ---------------------------------------------------------------------------
# Chaos semantics
# ---------------------------------------------------------------------------


def _two_node_wl(script):
    """Tiny 2-node workload: node 0 runs `script` at init (an EmitBuilder
    program), node 1 counts on_init invocations and received pings."""

    def on_init(ctx):
        eb = ctx.emits()
        is0 = ctx.node == jnp.int32(0)
        script(eb, is0)
        new = jnp.where(
            ctx.node == jnp.int32(1), ctx.state.at[0].set(ctx.state[0] + 1), ctx.state
        )
        return new, eb.build()

    def on_ping(ctx):
        return ctx.state.at[1].set(ctx.state[1] + 1), ctx.emits().build()

    return Workload(
        name="twonode", n_nodes=2, state_width=4, handlers=(on_init, on_ping),
        max_emits=8,
    )


class TestChaos:
    def test_kill_drops_inflight_events(self):
        # ping sent at t=0 (1-10ms latency); node 1 killed at t=0.5ms =>
        # epoch bump drops the delivery (task.rs:255-276 semantics)
        def script(eb, is0):
            eb.send(1, user_kind(1), (), when=is0)
            eb.after(500_000, KIND_KILL, 0, (1,), when=is0)

        wl = _two_node_wl(script)
        cfg = EngineConfig(pool_size=32)
        out = run_workload(wl, cfg, np.arange(8), 50)
        assert not np.asarray(out.alive)[:, 1].any()
        assert (np.asarray(out.node_state)[:, 1, 1] == 0).all()

    def test_restart_reruns_init(self):
        # kill at 0.5ms, restart at 1s: node 1's init runs again on a
        # fresh state row (init-task respawn, task.rs:279-291)
        def script(eb, is0):
            eb.after(500_000, KIND_KILL, 0, (1,), when=is0)
            eb.after(1_000_000_000, KIND_RESTART, 0, (1,), when=is0)
            # ping after restart is delivered to the new incarnation
            eb.after(2_000_000_000, user_kind(1), 0, when=is0)

        def on_init(ctx):
            eb = ctx.emits()
            script(eb, ctx.node == jnp.int32(0))
            new = jnp.where(
                ctx.node == jnp.int32(1),
                ctx.state.at[0].set(ctx.state[0] + 1),
                ctx.state,
            )
            return new, eb.build()

        def on_send_ping(ctx):
            eb = ctx.emits()
            eb.send(1, user_kind(2), ())
            return ctx.state, eb.build()

        def on_ping(ctx):
            return ctx.state.at[1].set(ctx.state[1] + 1), ctx.emits().build()

        wl = Workload(
            name="restart", n_nodes=2, state_width=4,
            handlers=(on_init, on_send_ping, on_ping), max_emits=8,
        )
        cfg = EngineConfig(pool_size=32)
        out = run_workload(wl, cfg, np.arange(8), 100)
        ns = np.asarray(out.node_state)
        assert np.asarray(out.alive)[:, 1].all()
        # state was reset by restart: init counter is 1 again (fresh row,
        # then one on_init), and the post-restart ping arrived
        assert (ns[:, 1, 0] == 1).all()
        assert (ns[:, 1, 1] == 1).all()

    def test_clog_delays_delivery_until_unclog(self):
        # link clogged from t=0; ping sent at t=1ms; unclog at t=5s.
        # The delivery must happen after 5s (clogged messages wait and
        # retry with backoff — net/mod.rs:341-355), not be dropped.
        def script(eb, is0):
            eb.after(0, KIND_CLOG, 0, (0, 1), when=is0)
            eb.send(1, user_kind(1), (), when=is0)
            eb.after(5_000_000_000, KIND_UNCLOG, 0, (0, 1), when=is0)

        wl = _two_node_wl(script)
        cfg = EngineConfig(pool_size=32)
        # one jitted 200-step program (200 un-jitted vmapped dispatches
        # ran op-by-op and took ~80 s — 20% of the whole suite)
        st = run_workload(wl, cfg, np.arange(4), 200)
        ns = np.asarray(st.node_state)
        assert (ns[:, 1, 1] == 1).all(), "clogged message must eventually deliver"
        # and the clock is past the unclog time on every seed
        assert (np.asarray(st.now) >= 5_000_000_000).all()

    def test_loss_drops_messages(self):
        def script(eb, is0):
            for _ in range(6):
                eb.send(1, user_kind(1), (), when=is0)

        wl = _two_node_wl(script)
        out_l = run_workload(
            wl, EngineConfig(pool_size=64, loss_p=0.7), np.arange(64), 30
        )
        got = np.asarray(out_l.node_state)[:, 1, 1]
        assert got.mean() < 4.0, "70% loss should drop most of 6 pings"
        out_0 = run_workload(wl, EngineConfig(pool_size=64), np.arange(64), 30)
        assert (np.asarray(out_0.node_state)[:, 1, 1] == 6).all()

    def test_time_limit_halts(self):
        wl = make_microbench(rounds=10**6)
        cfg = EngineConfig(pool_size=16, time_limit_ns=1_000_000)
        out = run_workload(wl, cfg, np.arange(4), 5000)
        assert np.asarray(out.halted).all()
        assert (np.asarray(out.now) <= 1_100_000).all()

    def test_pool_overflow_counted(self):
        def script(eb, is0):
            for _ in range(8):
                eb.send(1, user_kind(1), (), when=is0)

        wl = _two_node_wl(script)
        cfg = EngineConfig(pool_size=4)  # 2 init events leave 2 free slots
        out = run_workload(wl, cfg, np.arange(4), 30)
        assert (np.asarray(out.overflow) > 0).all()


# ---------------------------------------------------------------------------
# Workloads
# ---------------------------------------------------------------------------


class TestWorkloads:
    def test_pingpong_completes_exact_counts(self):
        wl = make_pingpong(rounds=7)
        out = run_workload(wl, EngineConfig(pool_size=64), np.arange(16), 400)
        assert np.asarray(out.halted).all()
        ns = np.asarray(out.node_state)
        assert (ns[:, 0, 0] == 2).all()  # both clients reported done
        assert (ns[:, 0, 1] == 14).all()  # 2 clients x 7 pings served

    def test_raft_elects_exactly_one_leader(self):
        wl = make_raft()
        cfg = EngineConfig(pool_size=128, loss_p=0.05)
        out = run_workload(wl, cfg, np.arange(128), 500)
        h = np.asarray(out.halted)
        assert h.all(), "every seed should elect a leader within 500 events"
        leaders = (np.asarray(out.node_state)[:, :, 0] == 2).sum(axis=1)
        assert (leaders == 1).all()
        # election latency is at least one timeout (150ms) on every seed
        assert (np.asarray(out.halt_time) >= 150_000_000).all()

    def test_raft_election_times_vary_with_seed(self):
        wl = make_raft()
        out = run_workload(wl, EngineConfig(pool_size=128), np.arange(32), 500)
        times = np.asarray(out.halt_time)
        assert len(set(times.tolist())) > 16

    def test_broadcast_survives_loss_and_partition(self):
        wl = make_broadcast(rounds=3)
        cfg = EngineConfig(pool_size=128, loss_p=0.1)
        out = run_workload(wl, cfg, np.arange(32), 600)
        assert np.asarray(out.halted).all()
        ns = np.asarray(out.node_state)
        assert (ns[:, 1:, 0] == 3).all(), "every peer saw the last round"

    def test_microbench_exact_ticks(self):
        wl = make_microbench(rounds=123)
        out = run_workload(wl, EngineConfig(pool_size=8), np.arange(8), 130)
        assert np.asarray(out.halted).all()
        assert (np.asarray(out.node_state)[:, 0, 0] == 123).all()


# ---------------------------------------------------------------------------
# Stats
# ---------------------------------------------------------------------------


class TestStats:
    def test_msg_count_matches_pingpong_math(self):
        # per client: rounds pings + 1 done; server: 2*rounds pongs
        wl = make_pingpong(rounds=4)
        out = run_workload(wl, EngineConfig(pool_size=64), np.arange(8), 300)
        expect = 2 * (4 + 1) + 2 * 4
        assert (np.asarray(out.msg_count) == expect).all()

    def test_sim_seconds_property(self):
        wl = make_microbench(rounds=10)
        out = run_workload(wl, EngineConfig(pool_size=8), np.arange(4), 20)
        secs = np.asarray(out.sim_seconds)
        assert (secs > 0).all()


class TestRunWhileAndCheckpoint:
    def test_run_while_matches_scan_for_halting_workload(self):
        from madsim_tpu.engine import make_run_while

        wl = make_pingpong(rounds=4)
        cfg = EngineConfig(pool_size=64)
        init = make_init(wl, cfg)
        st = init(np.arange(8, dtype=np.uint64))
        scan_out = jax.jit(make_run(wl, cfg, 300))(st)
        while_out = jax.jit(make_run_while(wl, cfg, 300))(st)
        assert np.asarray(while_out.halted).all()
        # halted seeds are frozen, so both paths end in the same state
        assert np.array_equal(
            np.asarray(scan_out.trace), np.asarray(while_out.trace)
        )
        assert np.array_equal(np.asarray(scan_out.now), np.asarray(while_out.now))

    def test_checkpoint_roundtrip_resumes_identically(self, tmp_path):
        from madsim_tpu.engine import load_checkpoint, save_checkpoint

        wl = make_raft()
        cfg = EngineConfig(pool_size=128)
        init = make_init(wl, cfg)
        st = init(np.arange(8, dtype=np.uint64))
        run_half = jax.jit(make_run(wl, cfg, 100))
        mid = run_half(st)
        path = str(tmp_path / "ckpt.npz")
        save_checkpoint(path, mid, cfg)
        resumed = load_checkpoint(path, cfg)
        a = run_half(mid)
        b = run_half(resumed)
        assert np.array_equal(np.asarray(a.trace), np.asarray(b.trace))
        assert np.array_equal(np.asarray(a.now), np.asarray(b.now))

    def test_checkpoint_rejects_other_config(self, tmp_path):
        from madsim_tpu.engine import load_checkpoint, save_checkpoint

        wl = make_microbench(rounds=5)
        cfg = EngineConfig(pool_size=8)
        st = make_init(wl, cfg)(np.arange(2, dtype=np.uint64))
        path = str(tmp_path / "c.npz")
        save_checkpoint(path, st, cfg)
        with pytest.raises(ValueError, match="different EngineConfig"):
            load_checkpoint(path, EngineConfig(pool_size=8, loss_p=0.5))

    def test_checkpoint_rejects_time_representation_mismatch(self, tmp_path):
        """A checkpoint saved under one ev_time representation refuses a
        declared resume under the other (auto-resolution is platform-
        dependent, so this is the cross-platform resume hazard)."""
        from madsim_tpu.engine import load_checkpoint, save_checkpoint

        wl = make_microbench(rounds=5)
        cfg = EngineConfig(pool_size=8)
        st = make_init(wl, cfg)(np.arange(2, dtype=np.uint64))
        path = str(tmp_path / "c.npz")
        save_checkpoint(path, st, cfg)
        saved32 = np.asarray(st.ev_time).dtype == np.int32
        # matching declaration loads fine; the opposite one is rejected
        load_checkpoint(path, cfg, time32=saved32)
        with pytest.raises(ValueError, match="ev_time dtype"):
            load_checkpoint(path, cfg, time32=not saved32)


class TestKvChaos:
    def test_kvchaos_durability_invariant_under_crash(self):
        """Config-5 shape: replicated KV with kill/restart chaos — every
        seed completes and the final committed write is durable on every
        replica at halt (re-sync after restart included)."""
        from madsim_tpu.engine import make_run_while
        from madsim_tpu.models import make_kvchaos

        wl = make_kvchaos(writes=10)
        cfg = EngineConfig(pool_size=160, loss_p=0.05)
        init = make_init(wl, cfg)
        out = jax.jit(make_run_while(wl, cfg, 8000))(
            init(np.arange(64, dtype=np.uint64))
        )
        h = np.asarray(out.halted)
        assert h.all()
        ns = np.asarray(out.node_state)
        assert (ns[:, -1, 0] == 10).all(), "client saw all commits"
        durable = (ns[:, 1:5, 0] >= 10).sum(axis=1)
        # RAM-only replicas: one crash can erase at most one post-ack
        # copy, so >= R-1 always; the rejoin/re-sync path makes full
        # durability the norm (exact on this fixed seed set)
        assert (durable >= 3).all(), "durability floor violated"
        assert (durable == 4).mean() >= 0.9
        assert (np.asarray(out.overflow) == 0).all()

    def test_kvchaos_deterministic(self):
        from madsim_tpu.engine import make_run_while
        from madsim_tpu.models import make_kvchaos

        wl = make_kvchaos(writes=5)
        cfg = EngineConfig(pool_size=160, loss_p=0.05)
        init = make_init(wl, cfg)
        run = jax.jit(make_run_while(wl, cfg, 4000))
        a = run(init(np.arange(8, dtype=np.uint64)))
        b = run(init(np.arange(8, dtype=np.uint64)))
        assert np.array_equal(np.asarray(a.trace), np.asarray(b.trace))


class TestPauseResume:
    def test_pause_holds_events_resume_releases(self):
        """Pause stashes a node's events; resume releases them — the
        batched form of Handle::pause/resume (task.rs:294-314)."""
        from madsim_tpu.engine import KIND_PAUSE, KIND_RESUME

        def script(eb, is0):
            eb.after(0, KIND_PAUSE, 0, (1,), when=is0)
            eb.send(1, user_kind(1), (), when=is0)
            eb.after(5_000_000_000, KIND_RESUME, 0, (1,), when=is0)

        wl = _two_node_wl(script)
        cfg = EngineConfig(pool_size=32)
        out = run_workload(wl, cfg, np.arange(8), 300)
        ns = np.asarray(out.node_state)
        # the ping eventually landed, but only after the 5s resume
        assert (ns[:, 1, 1] == 1).all()
        assert (np.asarray(out.now) >= 5_000_000_000).all()

    def test_kill_clears_pause(self):
        from madsim_tpu.engine import KIND_PAUSE

        def script(eb, is0):
            eb.after(0, KIND_PAUSE, 0, (1,), when=is0)
            eb.after(1_000_000, KIND_KILL, 0, (1,), when=is0)
            eb.after(2_000_000, KIND_RESTART, 0, (1,), when=is0)
            eb.send(1, user_kind(1), (), when=is0)
        # after restart the fresh node is unpaused: a later ping lands

        def on_init(ctx):
            eb = ctx.emits()
            script(eb, ctx.node == jnp.int32(0))
            eb.after(
                3_000_000_000, user_kind(2), 0,
                when=ctx.node == jnp.int32(0),
            )
            return ctx.state, eb.build()

        def on_ping(ctx):
            return ctx.state.at[1].set(ctx.state[1] + 1), ctx.emits().build()

        def on_late(ctx):
            eb = ctx.emits()
            eb.send(1, user_kind(1), ())
            return ctx.state, eb.build()

        wl = Workload(
            name="pausekill", n_nodes=2, state_width=4,
            handlers=(on_init, on_ping, on_late), max_emits=8,
        )
        out = run_workload(wl, EngineConfig(pool_size=32), np.arange(8), 100)
        ns = np.asarray(out.node_state)
        assert np.asarray(out.alive)[:, 1].all()
        assert (ns[:, 1, 1] == 1).all(), "post-restart ping delivered"


def test_zero_handler_workload_traces():
    # a chaos-only workload (no user handlers) must still compile: the
    # user lax.switch is skipped entirely
    wl = Workload(name="empty", n_nodes=2, state_width=2, handlers=())
    out = run_workload(wl, EngineConfig(pool_size=16), np.arange(4), 20)
    assert np.asarray(out.node_state).shape == (4, 2, 2)


def test_restart_restores_initial_rows():
    # restart resets the node to Workload.initial_state(), NOT zeros —
    # the oracle mirrors this via oracle_set_init_state
    init_rows = np.asarray([[7, 3], [9, 5]], np.int32)

    def on_init(ctx):
        eb = ctx.emits()
        # node 0 schedules: bump own state, then kill+restart node 1
        eb.after(1_000, user_kind(1), 1, when=ctx.node == jnp.int32(0))
        eb.after(5_000_000, 0, 0, (1,), when=ctx.node == jnp.int32(0))  # KIND_KILL
        eb.after(9_000_000, 1, 0, (1,), when=ctx.node == jnp.int32(0))  # KIND_RESTART
        return ctx.state, eb.build()

    def on_bump(ctx):
        return ctx.state.at[0].set(ctx.state[0] + 100), ctx.emits().build()

    wl = Workload(
        name="restart-init", n_nodes=2, state_width=2,
        handlers=(on_init, on_bump), max_emits=4, init_state=init_rows,
    )
    out = run_workload(wl, EngineConfig(pool_size=16), np.arange(4), 60)
    ns = np.asarray(out.node_state)
    # node 1 was bumped (7+100 -> wait: node 1 row is [9,5] -> 109),
    # then killed and restarted: back to its initial row [9, 5]
    assert (ns[:, 1, 0] == 9).all()
    assert (ns[:, 1, 1] == 5).all()
    # node 0 untouched: keeps its initial row
    assert (ns[:, 0, 0] == 7).all()


# raft (the flagship) gates every push; the other families' 4-variant
# crosses are compile-heavy and ride the full tier (`-m ""`), with the
# oracle bit-identical tests still covering each family by default
@pytest.mark.parametrize(
    "name",
    ["raft"]
    + [
        pytest.param(n, marks=pytest.mark.slow)
        for n in ["microbench", "pingpong", "broadcast", "kvchaos"]
    ],
)
def test_check_layouts_all_models(name):
    # the library form of the cross-backend check: dense and scatter
    # lowerings must agree (traces + state) for every benchmark workload.
    # Bench configs are time32-eligible, so this crosses the int32
    # offset representation with both layouts too (4 variants)
    from madsim_tpu.engine import EngineConfig, check_layouts, time32_eligible
    from madsim_tpu.models import BENCH_SPECS

    factory, cfg_kwargs, _seeds, _steps = BENCH_SPECS[name]
    wl, cfg = factory(), EngineConfig(**cfg_kwargs)
    assert time32_eligible(wl, cfg), "bench configs must allow int32 times"
    check_layouts(wl, cfg, np.arange(8), 150)


class TestTime32:
    def test_forced_time32_on_ineligible_config_raises(self):
        from madsim_tpu.engine import EngineConfig, make_step
        from madsim_tpu.models import make_raft

        # the default 10 s clog-backoff cap exceeds the int32 horizon
        wl, cfg = make_raft(), EngineConfig(pool_size=48)
        with pytest.raises(ValueError, match="not eligible"):
            make_step(wl, cfg, time32=True)

    def test_undeclared_delay_bound_is_ineligible(self):
        from madsim_tpu.engine import EngineConfig, time32_eligible
        from madsim_tpu.models import make_raft

        wl = make_raft()
        wl = type(wl)(**{**wl.__dict__, "delay_bound_ns": None})
        assert not time32_eligible(
            wl, EngineConfig(clog_backoff_max_ns=10_000_000)
        )

    def test_delay_past_horizon_counts_as_overflow(self):
        # a handler lying about delay_bound_ns must be caught loudly:
        # the emitted timer is clamped and counted into `overflow`
        from madsim_tpu.engine import (
            EngineConfig,
            Workload,
            make_init,
            make_run,
            user_kind,
        )

        def on_init(ctx):
            eb = ctx.emits()
            eb.after(3_000_000_000, user_kind(0), ctx.node)  # 3 s > 2^31 ns
            return ctx.state, eb.build()

        wl = Workload(
            name="liar",
            n_nodes=1,
            state_width=1,
            handlers=(on_init,),
            max_emits=1,
            delay_bound_ns=1_000,  # the lie
        )
        cfg = EngineConfig(pool_size=4, clog_backoff_max_ns=10_000_000)
        out = jax.jit(make_run(wl, cfg, 3, time32=True))(
            make_init(wl, cfg, time32=True)(np.arange(2, dtype=np.uint64))
        )
        assert int(np.asarray(out.overflow).sum()) >= 2

    def test_representation_mismatch_is_loud(self):
        # a state built under one time representation fed to a step
        # built under the other (the checkpoint save/resume hazard)
        # must raise at trace time, not silently misread offsets
        from madsim_tpu.engine import EngineConfig, make_init, make_run
        from madsim_tpu.models import BENCH_SPECS

        factory, kw, _, _ = BENCH_SPECS["raft"]
        wl, cfg = factory(), EngineConfig(**kw)
        state = make_init(wl, cfg, time32=True)(np.arange(2, dtype=np.uint64))
        with pytest.raises(TypeError, match="time32"):
            jax.jit(make_run(wl, cfg, 3, time32=False))(state)


def test_twophase_atomicity_under_chaos():
    # 2PC invariants across seeded chaos schedules: every transaction
    # decided, the final decision applied by every participant, and the
    # commit tally bounded by txns
    from madsim_tpu.models import make_twophase

    wl = make_twophase(txns=5)
    cfg = EngineConfig(pool_size=48, loss_p=0.03)
    out = run_workload(wl, cfg, np.arange(256), 1400)
    ns = np.asarray(out.node_state)
    assert bool(np.asarray(out.halted).all()), "all schedules complete"
    assert int(np.asarray(out.overflow).sum()) == 0
    coord = ns[:, 0]
    assert ((coord[:, 4] + coord[:, 5]) == 5).all(), "every txn decided"
    assert (ns[:, 1:5, 2] == 5).all(), "final decision reached everyone"
    # atomicity: every participant's stored decision VALUE for the final
    # transaction matches the coordinator's (phase 1 = commit)
    coord_committed = (coord[:, 1] == 1).astype(np.int32)
    assert (ns[:, 1:5, 4] == coord_committed[:, None]).all(), (
        "a participant disagrees with the coordinator's final decision"
    )


def test_paxos_agreement_under_chaos():
    """Single-decree paxos safety across 1,024 chaos schedules: every
    seed decides (liveness within the cap), all deciders agree on ONE
    value (agreement), that value is some proposer's (validity), and a
    majority of acceptors hold it at halt (the choosing-quorum witness:
    once chosen, later ballots can only carry the chosen value)."""
    from madsim_tpu.engine import make_run_while
    from madsim_tpu.models import make_paxos
    from madsim_tpu.models.paxos import P_DEC, A_VAL

    a, p = 5, 3
    wl = make_paxos()
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    out = jax.jit(make_run_while(wl, cfg, 2000))(
        make_init(wl, cfg)(np.arange(1024, dtype=np.uint64))
    )
    h = np.asarray(out.halted)
    assert h.all(), "every schedule must decide within the cap"
    assert int(np.asarray(out.overflow).sum()) == 0
    ns = np.asarray(out.node_state)
    dec = ns[:, a:, P_DEC]
    acc_val = ns[:, :a, A_VAL]
    for s in range(ns.shape[0]):
        d = dec[s][dec[s] != 0]
        assert d.size > 0, f"seed {s}: halted without a decision"
        assert (d == d[0]).all(), f"seed {s}: agreement violated {dec[s]}"
        assert 1 <= d[0] <= p, f"seed {s}: invalid value {d[0]}"
        assert (acc_val[s] == d[0]).sum() >= a // 2 + 1, (
            f"seed {s}: no acceptor-majority witness for {d[0]}"
        )


def test_durable_cols_survive_restart():
    """Workload.durable_cols — the FsSim power-fail analog: RESTART
    restores the initial row for volatile columns only; durable
    columns keep their pre-kill values."""
    from madsim_tpu.engine import EmitBuilder  # noqa: F401 (doc import)
    from madsim_tpu.engine import Workload, make_run, user_kind

    def on_init(ctx):
        eb = ctx.emits()
        # first incarnation: write both columns, then kill+restart self
        first = ctx.state[0] == jnp.int32(0)
        new = ctx.state.at[0].set(7).at[1].set(9)
        eb.after(1_000_000, KIND_KILL, 0, (jnp.int32(0),), when=first)
        eb.after(2_000_000, KIND_RESTART, 0, (jnp.int32(0),), when=first)
        return jnp.where(first, new, ctx.state), eb.build()

    wl = Workload(
        name="durable-probe",
        n_nodes=1,
        state_width=2,
        handlers=(on_init,),
        max_emits=2,
        durable_cols=(0,),
    )
    out = jax.jit(make_run(wl, EngineConfig(pool_size=8), 10))(
        make_init(wl, EngineConfig(pool_size=8))(np.arange(4, dtype=np.uint64))
    )
    ns = np.asarray(out.node_state)
    # post-restart on_init sees state[0]==7 (durable, not 'first'), so
    # it writes nothing: col 0 kept 7, col 1 reset to the initial 0
    assert (ns[:, 0, 0] == 7).all(), "durable column lost on restart"
    assert (ns[:, 0, 1] == 0).all(), "volatile column survived restart"


def test_paxos_durable_acceptor_kills_stay_safe():
    """Classic paxos with stable acceptor storage: the chaos kill hits
    an ACCEPTOR, whose (promised, accepted) columns survive via
    durable_cols — agreement must still hold on every schedule."""
    from madsim_tpu.engine import make_run_while
    from madsim_tpu.models import make_paxos
    from madsim_tpu.models.paxos import A_VAL, P_DEC

    a, p = 5, 3
    wl = make_paxos(durable_acceptors=True)
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    out = jax.jit(make_run_while(wl, cfg, 2000))(
        make_init(wl, cfg)(np.arange(512, dtype=np.uint64))
    )
    assert np.asarray(out.halted).all()
    assert int(np.asarray(out.overflow).sum()) == 0
    ns = np.asarray(out.node_state)
    dec = ns[:, a:, P_DEC]
    for s in range(ns.shape[0]):
        d = dec[s][dec[s] != 0]
        assert d.size and (d == d[0]).all() and 1 <= d[0] <= p, s
        assert (ns[s, :a, A_VAL] == d[0]).sum() >= a // 2 + 1, s


class TestRaftLog:
    """Raft log replication: safety invariant + lowering equivalence."""

    def _final_states(self, n_seeds=1024, durable=False):
        from madsim_tpu.engine import EngineConfig, make_init, make_run_while
        from madsim_tpu.models import make_raftlog

        wl = make_raftlog(durable=durable)
        cfg = EngineConfig(
            pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
        )
        out = jax.jit(make_run_while(wl, cfg, 4000))(
            make_init(wl, cfg)(np.arange(n_seeds, dtype=np.uint64))
        )
        return jax.block_until_ready(out)

    def _assert_majority_prefix(self, out):
        from madsim_tpu.models.raftlog import COMMIT, LOG0, LOGLEN

        h = np.asarray(out.halted)
        ns = np.asarray(out.node_state)
        assert h.all(), "every seed must finish its writes"
        assert int(np.asarray(out.overflow).sum()) == 0
        W = 4
        for s in range(ns.shape[0]):
            rows = ns[s]
            committers = [i for i in range(5) if rows[i][COMMIT] == W]
            assert committers, f"seed {s}: halted without a full commit"
            # compare entry VALUES (low byte): a legal win-time re-stamp
            # can leave equal values under different term bytes on nodes
            # a delayed ack raced against a re-election
            ref = rows[committers[0]][LOG0:LOG0 + W] & 0xFF
            match = sum(
                1
                for i in range(5)
                if rows[i][LOGLEN] >= W
                and ((rows[i][LOG0:LOG0 + W] & 0xFF) == ref).all()
            )
            assert match >= 3, f"seed {s}: committed log on {match}/5 nodes"

    def test_committed_entries_on_majority(self):
        # the raft safety claim, checked across seeds, elections and the
        # seeded leader kill/restart: at halt, the committed log is
        # present in order with equal values on a majority of nodes
        self._assert_majority_prefix(self._final_states())

    def test_committed_entries_on_majority_durable(self):
        # crash-recovery raft: same invariant with the paper's persistent
        # state (term, votedFor, log) surviving the kill — the restart no
        # longer wipes the log, so safety must hold through genuine
        # recovery rather than reinstall-from-leader
        self._assert_majority_prefix(self._final_states(durable=True))

    @pytest.mark.slow
    def test_check_layouts_raftlog(self):
        from madsim_tpu.engine import EngineConfig, check_layouts, time32_eligible
        from madsim_tpu.models import make_raftlog

        wl = make_raftlog()
        cfg = EngineConfig(
            pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
        )
        assert time32_eligible(wl, cfg)
        check_layouts(wl, cfg, np.arange(8), 500)


@pytest.mark.slow
def test_config_fuzz_layouts_agree():
    """Randomized configs — including overflow-inducing tiny pools,
    total packet loss, degenerate latency ranges and mid-run time
    limits — must keep every lowering combination (dense/scatter x
    int64/int32 when eligible) bit-identical. The drop rule under pool
    overflow is deterministic (rank-based), so even lossy runs agree."""
    from madsim_tpu.engine import EngineConfig, check_layouts
    from madsim_tpu.models import make_broadcast, make_raft

    rng = np.random.RandomState(20260730)
    for case in range(6):
        lat_min = int(rng.randint(1, 5_000_000))
        # case 0 pins the degenerate zero-span latency range (the
        # max(span, 1) clamp in core.py); later cases draw freely
        span = 0 if case == 0 else int(rng.randint(0, 10_000_000))
        cfg = EngineConfig(
            pool_size=int(rng.choice([8, 12, 40, 64])),
            lat_min_ns=lat_min,
            lat_max_ns=lat_min + span,
            loss_p=float(rng.choice([0.0, 0.05, 0.5, 1.0])),
            proc_min_ns=50,
            proc_max_ns=int(rng.choice([50, 100, 1000])),
            clog_backoff_max_ns=2_000_000_000,
            time_limit_ns=int(rng.choice([0, 200_000_000])),
        )
        wl = make_raft() if case % 2 == 0 else make_broadcast()
        check_layouts(wl, cfg, np.arange(6, dtype=np.uint64), 120)


# tier-1 budget (ROADMAP note): 4,096 seeds x 400 steps is this file's
# second-heaviest program; the snapshot model's engine values are
# oracle-pinned tier-1 (test_oracle: snapshot traces bit-identical) and
# the conservation sweep rides test-full / the soaks.
@pytest.mark.slow
def test_snapshot_conservation_under_reordering():
    """Lai-Yang snapshot invariant across 4,096 seeded schedules: the
    recorded cut (balances + channel state) sums EXACTLY to the minted
    total on every seed, despite transfers crossing the cut under
    random message reordering; all seeds terminate via the witness
    count, all nodes end red, and live balances re-conserve at halt."""
    from madsim_tpu.models import make_snapshot
    from madsim_tpu.models.snapshot import BAL, CHANIN, COLOR, RCNT, RECBAL

    n, b0, k = 5, 1000, 6
    wl = make_snapshot(n_nodes=n, balance=b0, n_sends=k)
    cfg = EngineConfig(pool_size=96)
    out = run_workload(wl, cfg, np.arange(4096), 400)
    assert bool(np.asarray(out.halted).all()), "every schedule terminates"
    assert int(np.asarray(out.overflow).sum()) == 0
    ns = np.asarray(out.node_state)
    assert (ns[:, :, COLOR] == 1).all(), "every node turned red"
    cut = ns[:, :, RECBAL].sum(1) + ns[:, :, CHANIN].sum(1)
    assert (cut == n * b0).all(), "consistent-cut conservation violated"
    assert (ns[:, :, BAL].sum(1) == n * b0).all(), "live conservation"
    assert (ns[:, 0, RCNT] == n * k + n * (n - 1)).all()
    # the cut is non-trivial: some schedules must actually capture
    # in-flight money in channel state
    assert (ns[:, :, CHANIN].sum(1) > 0).any()


def test_snapshot_layout_cross():
    from madsim_tpu.engine import check_layouts
    from madsim_tpu.models import make_snapshot

    check_layouts(make_snapshot(), EngineConfig(pool_size=96),
                  np.arange(8), 300)
