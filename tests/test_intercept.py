"""Determinism substrate: stdlib interposition inside the sim context
(the analog of the reference's libc overrides — rand.rs:174-240,
system_time.rs:6-109, task.rs:711-725)."""

import os
import random
import threading
import time
import uuid

import pytest

import madsim_tpu as ms


def _run(seed, coro_fn):
    return ms.Runtime(seed=seed).block_on(coro_fn())


def test_stdlib_random_is_deterministic_per_seed():
    async def wl():
        return [random.random() for _ in range(5)] + [random.randint(0, 10**9)]

    assert _run(42, wl) == _run(42, wl)
    assert _run(42, wl) != _run(43, wl)


def test_os_urandom_and_uuid_deterministic():
    async def wl():
        return os.urandom(16), str(uuid.uuid4())

    assert _run(7, wl) == _run(7, wl)
    assert _run(7, wl) != _run(8, wl)


def test_time_time_is_simulated():
    async def wl():
        t0 = time.time()
        await ms.sleep(5.0)
        return t0, time.time()

    t0, t1 = _run(3, wl)
    assert 1_640_995_200 <= t0 <= 1_672_531_200  # year 2022
    assert 4.9 < t1 - t0 < 5.1


def test_monotonic_is_simulated():
    async def wl():
        m0 = time.monotonic()
        await ms.sleep(2.0)
        return time.monotonic() - m0

    assert 1.9 < _run(3, wl) < 2.1


def test_blocking_sleep_advances_virtual_clock():
    async def wl():
        m0 = time.monotonic_ns()
        time.sleep(1.5)  # must not block real time
        return time.monotonic_ns() - m0

    assert _run(3, wl) == 1_500_000_000


def test_threads_forbidden_in_simulation():
    async def wl():
        t = threading.Thread(target=lambda: None)
        with pytest.raises(RuntimeError, match="cannot create system threads"):
            t.start()
        return True

    assert _run(1, wl)


def test_random_seed_forbidden_in_simulation():
    async def wl():
        with pytest.raises(RuntimeError, match="forbidden"):
            random.seed(0)
        return True

    assert _run(1, wl)


def test_outside_sim_stdlib_untouched():
    # Dispatchers fall through to the real implementations off-thread
    # (the dlsym(RTLD_NEXT) analog).
    ms.Runtime(seed=1).block_on(_noop())
    now = time.time()
    assert now > 1_700_000_000  # real present-day clock, not year 2022
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    random.seed(123)
    a = random.random()
    random.seed(123)
    assert random.random() == a


async def _noop():
    return None


def test_available_parallelism_reflects_node_cores():
    async def wl():
        h = ms.Handle.current()
        got = {}

        async def probe():
            got["cores"] = ms.available_parallelism()
            got["cpu_count"] = os.cpu_count()

        node = h.create_node().cores(4).build()
        node.spawn(probe())
        await ms.sleep(1.0)
        return got

    got = _run(1, wl)
    assert got["cores"] == 4
    assert got["cpu_count"] == 4
