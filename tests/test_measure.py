"""engine/measure.py: the jitter-proof throughput harness.

The measurement must be *about* the same computation the engine runs:
the repeat program's scalar reductions have to equal what separate
compacted runs of the same seed blocks produce, or the sweep's numbers
describe a different program than the one shipped.
"""

import numpy as np

from madsim_tpu.engine import EngineConfig, make_init, make_run_compacted
from madsim_tpu.engine.measure import (
    make_repeat_program,
    measure_latency,
    measure_throughput,
    null_dispatch_stats,
)
from madsim_tpu.models import make_microbench, make_raft


def test_repeat_program_matches_separate_runs():
    # packing equivalence is model-agnostic; microbench's small step
    # body compiles ~3x faster than raft's (the raft repeat program is
    # exercised for real by every bench.py run)
    wl = make_microbench(rounds=40)
    cfg = EngineConfig(pool_size=16)
    n_seeds, repeats, seed_mod = 32, 3, 64
    program = make_repeat_program(wl, cfg, 400, n_seeds, seed_mod, min_size=8)
    sim_ns, ovf, halted = (int(x) for x in program(np.uint64(5), repeats))

    init = make_init(wl, cfg)
    run = make_run_compacted(
        wl, cfg, 400, min_size=8, fields=("now", "overflow", "halted")
    )
    want_ns = want_ovf = want_halted = 0
    for r in range(repeats):
        seeds = (5 + r * n_seeds + np.arange(n_seeds, dtype=np.uint64)) % seed_mod
        out = run(init(seeds))
        want_ns += int(np.asarray(out.now).sum())
        want_ovf += int(np.asarray(out.overflow).sum())
        want_halted += int(np.asarray(out.halted).sum())
    assert (sim_ns, ovf, halted) == (want_ns, want_ovf, want_halted)
    assert halted == repeats * n_seeds


def test_measure_throughput_reports_quotable_cell():
    wl = make_microbench(rounds=5)
    cfg = EngineConfig(pool_size=8)
    rec = measure_throughput(
        wl, cfg, 200, 64, target_wall_s=0.2, n_measure=2,
        seed_mod=128, min_size=16,
    )
    assert rec["overflow"] == 0
    assert rec["all_halted"]
    assert rec["sim_s_per_s_median"] > 0
    assert rec["sim_s_per_s_min"] <= rec["sim_s_per_s_median"] <= rec["sim_s_per_s_max"]
    assert len(rec["dispatch_walls_s"]) == 2
    assert rec["repeats"] >= 1


def test_measure_latency_reports_quotable_cell():
    # the single-seed latency analog (bench.py's pingpong quote)
    wl = make_microbench(rounds=5)
    cfg = EngineConfig(pool_size=8)
    rec = measure_latency(
        wl, cfg, 200, target_wall_s=0.2, n_measure=2, seed_mod=128
    )
    assert rec["overflow"] == 0
    assert rec["all_halted"]
    assert rec["n_seeds"] == 1
    assert rec["wall_us_per_sim_median"] > 0
    assert rec["sim_s_per_s"] > 0
    assert len(rec["dispatch_walls_s"]) == 2
    assert rec["repeats"] >= 32


def test_null_dispatch_stats_shape():
    s = null_dispatch_stats(n=5)
    assert s["n"] == 5
    assert 0 <= s["min_ms"] <= s["median_ms"] <= s["max_ms"]


def test_bench_configs_mirror_bench_specs():
    """bench.py's parent stays jax-free by mirroring the seed/step table
    of models.BENCH_SPECS; drift between the two would silently measure
    a different spec than the one certified by the oracle artifacts."""
    import importlib.util
    import os

    from madsim_tpu.models import BENCH_SPECS

    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_mod", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert set(bench.CONFIGS) == set(BENCH_SPECS)
    for name, (n_seeds, n_steps) in bench.CONFIGS.items():
        _f, _cfg, spec_seeds, spec_steps = BENCH_SPECS[name]
        assert (n_seeds, n_steps) == (spec_seeds, spec_steps), name
