"""madsim_tpu.explore — coverage taps, plan device parity, mutation,
and campaign determinism.

The subsystem's contract is replayability: the whole exploration
campaign is a pure function of its root seed, corpus entries replay to
their recorded trace hashes, and the engine's coverage taps never
perturb the simulation they observe. Each test pins one clause.
"""

import dataclasses

import numpy as np
import pytest

from madsim_tpu import explore
from madsim_tpu.chaos import (
    ClockSkew,
    CrashStorm,
    Duplicate,
    FaultPlan,
    FlappingPartition,
    GrayFailure,
    LiteralPlan,
    Partition,
    PauseStorm,
    stack_plan_rows,
)
from madsim_tpu.check import election_safety, read_your_writes, stale_reads
from madsim_tpu.engine import EngineConfig, search_seeds
from madsim_tpu.engine.rng import PURPOSE_EXPLORE
from madsim_tpu.explore.mutate import HostStream, PlanSpace, mutate_plan
from madsim_tpu.models import make_kvchaos, make_raft
from madsim_tpu.models.raft import OP_ELECT

NODES = (0, 1, 2, 3, 4)

RAFT_CFG = EngineConfig(pool_size=64, loss_p=0.02)
RAFT_PLAN = FaultPlan((
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="raft-explore-test")

MIXED_PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=2),
    PauseStorm(targets=(0, 4)),
    Partition(targets=NODES, asymmetric=True, partial_p=0.7),
    FlappingPartition(targets=NODES, n_cycles=2),
    GrayFailure(targets=NODES, n_links=2),
    Duplicate(),
    ClockSkew(targets=(0, 1, 2)),
), name="mixed")


def _raft_wl():
    return make_raft(record=True)


def _elect_inv(h):
    return election_safety(h, elect_op=OP_ELECT)


class TestEngineCoverage:
    def test_cov_off_and_on_identical_traces(self):
        """Coverage is derived state: enabling it changes no value."""
        wl = _raft_wl()
        inv = lambda v: np.ones(v["halted"].shape[0], bool)  # noqa: E731
        r0 = search_seeds(wl, RAFT_CFG, inv, n_seeds=16, max_steps=600)
        r1 = search_seeds(
            wl, RAFT_CFG, inv, n_seeds=16, max_steps=600, cov_words=16
        )
        assert np.array_equal(r0.traces, r1.traces)
        assert r0.cov is None
        assert r1.cov.shape == (16, 16) and r1.cov.dtype == np.uint32
        assert r1.cov.any(), "a raft election run must set coverage bits"

    def test_cov_identical_across_layouts_and_compact(self):
        wl = _raft_wl()
        inv = lambda v: np.ones(v["halted"].shape[0], bool)  # noqa: E731
        kw = dict(n_seeds=16, max_steps=600, cov_words=16)
        base = search_seeds(wl, RAFT_CFG, inv, layout="scatter", **kw)
        dense = search_seeds(wl, RAFT_CFG, inv, layout="dense", **kw)
        comp = search_seeds(wl, RAFT_CFG, inv, compact=True, **kw)
        assert np.array_equal(base.cov, dense.cov)
        assert np.array_equal(base.cov, comp.cov)

    def test_cov_words_must_be_power_of_two(self):
        from madsim_tpu.engine import make_init

        with pytest.raises(ValueError, match="power of two"):
            make_init(_raft_wl(), RAFT_CFG, cov_words=24)

    def test_explicit_seeds_match_range_sweep(self):
        wl = _raft_wl()
        inv = lambda v: np.ones(v["halted"].shape[0], bool)  # noqa: E731
        full = search_seeds(wl, RAFT_CFG, inv, n_seeds=8, max_steps=600)
        some = search_seeds(
            wl, RAFT_CFG, inv, seeds=np.array([2, 5, 7], np.uint64),
            max_steps=600,
        )
        assert np.array_equal(some.traces, full.traces[[2, 5, 7]])


class TestPlanDeviceParity:
    def test_jnp_compile_bit_identical(self):
        """The device (jnp) plan materialization path and the numpy
        path are the same function — bit-identical arrays."""
        seeds = np.arange(257, dtype=np.uint64) * np.uint64(2654435761)
        rows_np = MIXED_PLAN.compile_batch(seeds)
        rows_dev = MIXED_PLAN.compile_batch(seeds, device=True)
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(
                np.asarray(getattr(rows_np, f)),
                np.asarray(getattr(rows_dev, f)),
            ), f"device-parity divergence in {f}"

    def test_literal_device_parity(self):
        lp = MIXED_PLAN.literalize(42)
        seeds = np.arange(5, dtype=np.uint64)
        a = lp.compile_batch(seeds)
        b = lp.compile_batch(seeds, device=True)
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
            )


class TestFlappingPartition:
    def test_redraws_sides_each_cycle(self):
        spec = FlappingPartition(targets=NODES, n_cycles=2)
        plan = FaultPlan((spec,), name="flap")
        edges = len(NODES) * (len(NODES) - 1) // 2
        assert spec.slots == 2 * 2 * edges
        seeds = np.arange(64, dtype=np.uint64)
        rows = plan.compile_batch(seeds)
        c0 = np.asarray(rows.valid)[:, : 2 * edges]
        c1 = np.asarray(rows.valid)[:, 2 * edges:]
        # both cycles cut something on every seed...
        assert c0.any(axis=1).all() and c1.any(axis=1).all()
        # ...and the cut sides differ between cycles for most seeds
        # (independent subset draws)
        assert (c0 != c1).any(axis=1).sum() > 32
        # cycle 1 strictly follows cycle 0's heal on every seed
        t = np.asarray(rows.time)
        heal0 = t[:, 1]  # slot 1 = first cycle's first unclog (at+dur)
        cut1 = t[:, 2 * edges]
        assert (cut1 > heal0).all()

    def test_validation(self):
        with pytest.raises(ValueError, match="n_cycles"):
            FlappingPartition(targets=NODES, n_cycles=0)
        with pytest.raises(ValueError, match="two target"):
            FlappingPartition(targets=(1,))


class TestPlanHooks:
    def test_templates_align_with_compiled_slots(self):
        tmpl = MIXED_PLAN.slot_templates()
        assert len(tmpl) == MIXED_PLAN.slots
        rows = MIXED_PLAN.compile_batch(np.arange(3, dtype=np.uint64))
        assert [t.kind for t in tmpl] == [int(k) for k in rows.kind[0]]

    def test_literalize_replays_identical_rows(self):
        lp = MIXED_PLAN.literalize(99)
        assert lp.slots == MIXED_PLAN.slots
        a = MIXED_PLAN.compile_batch(np.asarray([99], np.uint64))
        b = lp.compile_batch(np.asarray([99], np.uint64))
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
            )

    def test_serialization_round_trip(self):
        lp = MIXED_PLAN.literalize(7)
        lp2 = LiteralPlan.from_dict(lp.to_dict())
        assert lp2.hash() == lp.hash()
        assert lp2.events == lp.events

    def test_stack_plan_rows_matches_batch_compile(self):
        plans = [MIXED_PLAN.literalize(s) for s in (3, 4, 5)]
        stacked = stack_plan_rows(plans)
        direct = MIXED_PLAN.compile_batch(np.asarray([3, 4, 5], np.uint64))
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(
                np.asarray(getattr(stacked, f)),
                np.asarray(getattr(direct, f)),
            )


class TestMutate:
    def test_deterministic_and_fresh(self):
        space = PlanSpace(MIXED_PLAN)
        parent = MIXED_PLAN.literalize(11)
        a = mutate_plan(parent, space, HostStream(1, 2, PURPOSE_EXPLORE))
        b = mutate_plan(parent, space, HostStream(1, 2, PURPOSE_EXPLORE))
        c = mutate_plan(parent, space, HostStream(3, 4, PURPOSE_EXPLORE))
        assert a.hash() == b.hash(), "same stream must breed the same child"
        assert a.hash() != parent.hash(), "a child must differ from parent"
        assert c.hash() != a.hash(), "different streams should diverge"

    def test_slot_count_preserved(self):
        space = PlanSpace(MIXED_PLAN)
        parent = MIXED_PLAN.literalize(11)
        st = HostStream(9, 9, PURPOSE_EXPLORE)
        for _ in range(20):
            child = mutate_plan(parent, space, st, max_ops=3)
            assert child.slots == parent.slots
            parent = child

    def test_degenerate_pair_targets_rejected(self):
        # a pair/slow slot with one distinct target cannot draw "some
        # OTHER target": the host mutator would crash mid-campaign and
        # the device mutator would silently breed b == a — the space is
        # refused up front on both paths instead
        plan = FaultPlan(
            (GrayFailure(targets=(2, 2), n_links=1),), name="degen"
        )
        with pytest.raises(ValueError, match="distinct targets"):
            PlanSpace(plan)


class TestCoverageAccounting:
    def test_admit_sequential_semantics(self):
        g = np.zeros(2, np.uint32)
        batch = np.array(
            [[1, 0], [1, 0], [3, 0], [0, 8]], np.uint32
        )
        new_bits, merged = explore.admit(batch, g)
        # row 0 sets bit0 (new); row 1 sets nothing new; row 2 adds
        # bit1; row 3 adds one bit in word 1
        assert new_bits.tolist() == [1, 0, 1, 1]
        assert merged.tolist() == [3, 8]
        assert explore.popcount(merged) == 3

    def test_merge_coverage_sharded_equals_host(self):
        from madsim_tpu.parallel import make_mesh, merge_coverage

        rng = np.random.default_rng(0)
        bm = rng.integers(0, 2**32, size=(64, 8), dtype=np.uint64).astype(
            np.uint32
        )
        host = explore.merge(bm)
        mesh = make_mesh()
        assert np.array_equal(merge_coverage(bm, mesh), host)
        assert np.array_equal(merge_coverage(bm), host)


class TestCampaign:
    """The determinism clauses of the ISSUE: same root seed => same
    corpus, coverage bitmap, and violation set — across runs and across
    engine lowerings — and stored entries replay their trace hash."""

    KW = dict(
        generations=3, batch=24, root_seed=11, max_steps=800,
        cov_words=16, history_invariant=_elect_inv,
    )

    def _fingerprint(self, rep):
        return (
            [(e.id, e.seed, e.plan.hash(), e.trace, e.new_bits)
             for e in rep.corpus],
            rep.cov_map.tolist(),
            [(e.seed, e.trace) for e in rep.violations],
            rep.curve,
        )

    def test_same_root_identical_campaign(self):
        a = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **self.KW)
        b = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **self.KW)
        assert self._fingerprint(a) == self._fingerprint(b)
        assert a.sims == 3 * 24

    def test_compact_and_layouts_identical(self):
        base = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **self.KW)
        comp = explore.run(
            _raft_wl(), RAFT_CFG, RAFT_PLAN, compact=True, **self.KW
        )
        dense = explore.run(
            _raft_wl(), RAFT_CFG, RAFT_PLAN, layout="dense", **self.KW
        )
        assert self._fingerprint(base) == self._fingerprint(comp)
        assert self._fingerprint(base) == self._fingerprint(dense)

    def test_corpus_entry_replays_trace(self):
        rep = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **self.KW)
        assert rep.corpus, "campaign admitted nothing"
        # one generation-0 entry and one bred entry, if present
        picks = [rep.corpus[0]]
        bred = [e for e in rep.corpus if e.generation > 0]
        if bred:
            picks.append(bred[-1])
        for e in picks:
            r = explore.replay_entry(
                _raft_wl(), RAFT_CFG, e, history_invariant=_elect_inv,
                max_steps=800,
            )
            assert int(r.traces[0]) == e.trace

    def test_different_root_differs(self):
        a = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **self.KW)
        kw = dict(self.KW)
        kw["root_seed"] = 12
        b = explore.run(_raft_wl(), RAFT_CFG, RAFT_PLAN, **kw)
        assert self._fingerprint(a) != self._fingerprint(b)


@pytest.mark.slow
class TestCampaignFindsViolations:
    def test_kvchaos_mutant_found_and_replayed(self):
        """The lost-write mutant is found by a tiny campaign, the
        violating entry replays to its stored trace, and the stored
        plan feeds shrink_plan."""
        wl = make_kvchaos(writes=6, record=True, bug=True, chaos=False)
        cfg = EngineConfig(pool_size=160, loss_p=0.05)
        plan = FaultPlan((
            CrashStorm(targets=(1, 2, 3, 4), n=2,
                       down_min_ns=50_000_000, down_max_ns=250_000_000),
        ), name="kv-explore-test")
        box = {}

        def hinv(h):
            box["ok"] = stale_reads(h) & read_your_writes(h)
            return box["ok"]

        rep = explore.run(
            wl, cfg, plan, history_invariant=hinv, generations=3,
            batch=48, root_seed=3, max_steps=3000, cov_words=16,
        )
        assert rep.violations, "mutant not caught by the campaign"
        e = rep.violations[0]
        r = explore.replay_entry(
            wl, cfg, e, history_invariant=hinv, max_steps=3000
        )
        assert int(r.traces[0]) == e.trace
        assert not bool(r.ok[0]), "replay must reproduce the violation"
        from madsim_tpu.chaos import shrink_plan

        res = shrink_plan(
            wl, cfg, e.seed, e.plan, history_invariant=hinv,
            max_steps=3000,
        )
        assert len(res.events) >= 1
        assert res.trace != 0
