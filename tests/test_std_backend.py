"""The real-world backend (madsim_tpu.std): same API, real I/O.

Mirrors the reference's std-side duality (C26/C29): the tag-matching
Endpoint + typed RPC running on real loopback TCP, real fs, real time —
so application code written for the simulator deploys unchanged.
"""

import asyncio

import pytest

from madsim_tpu.std import fs as std_fs
from madsim_tpu.std import net as std_net
from madsim_tpu.std import time as std_time


def run(coro):
    return asyncio.run(coro)


def test_endpoint_tag_matching_over_loopback():
    async def main():
        a = await std_net.Endpoint.bind("127.0.0.1:0")
        b = await std_net.Endpoint.bind("127.0.0.1:0")
        await a.send_to(b.local_addr, 7, {"hi": 1})
        payload, src = await b.recv_from(7)
        assert payload == {"hi": 1}
        # reply to the announced canonical source address
        await b.send_to(src, 9, "pong")
        payload2, _ = await a.recv_from(9)
        assert payload2 == "pong"
        # tag isolation: tag 7 waiter doesn't see tag 8
        await a.send_to(b.local_addr, 8, "eight")
        await a.send_to(b.local_addr, 7, "seven")
        p7, _ = await b.recv_from(7)
        p8, _ = await b.recv_from(8)
        assert (p7, p8) == ("seven", "eight")
        await a.close()
        await b.close()

    run(main())


class Echo:
    """Request types live at module scope — the analog of the reference's
    derived Request structs (pickle, like bincode, needs nameable types)."""

    def __init__(self, text):
        self.text = text


class Boom:
    pass


class Nobody:
    pass


class Put:
    def __init__(self, key):
        self.key = key


def test_rpc_roundtrip_and_errors():
    async def main():
        server = await std_net.Endpoint.bind("127.0.0.1:0")
        client = await std_net.Endpoint.bind("127.0.0.1:0")

        async def echo(req):
            return req.text.upper()

        async def boom(req):
            raise ValueError("kapow")

        server.add_rpc_handler(Echo, echo)
        server.add_rpc_handler(Boom, boom)
        assert await client.call(server.local_addr, Echo("hello")) == "HELLO"
        with pytest.raises(ValueError, match="kapow"):
            await client.call(server.local_addr, Boom())
        # timeout on a request nobody serves
        with pytest.raises(asyncio.TimeoutError):
            await client.call(server.local_addr, Nobody(), timeout=0.2)
        await server.close()
        await client.close()

    run(main())


def test_rpc_with_data_payload():
    async def main():
        server = await std_net.Endpoint.bind("127.0.0.1:0")
        client = await std_net.Endpoint.bind("127.0.0.1:0")
        stored = {}

        async def put(req, data):
            stored[req.key] = data
            return len(data), b"ack"

        server.add_rpc_handler_with_data(Put, put)
        n, data = await client.call_with_data(
            server.local_addr, Put("k"), b"\x00" * 4096
        )
        assert n == 4096 and data == b"ack"
        assert stored["k"] == b"\x00" * 4096
        await server.close()
        await client.close()

    run(main())


def test_std_fs_roundtrip(tmp_path):
    async def main():
        p = tmp_path / "blob"
        f = await std_fs.File.create(p)
        await f.write_all_at(b"hello world", 0)
        await f.sync_all()
        assert (await f.read_at(5, 6)) == b"world"
        meta = await f.metadata()
        assert meta.len == 11
        await f.set_len(5)
        assert (await std_fs.metadata(p)).len == 5
        assert await std_fs.read(p) == b"hello"
        f.close()

    run(main())


def test_std_time():
    async def main():
        t0 = std_time.now()
        await std_time.sleep(0.05)
        assert std_time.now() - t0 >= 0.04
        with pytest.raises(std_time.Elapsed):
            await std_time.timeout(0.05, asyncio.sleep(5))

    run(main())
