"""Chaos tests for the raft KV example — the MadRaft-style application
suite the reference ecosystem exists to enable (tonic-example's
client_crash/server_crash tests, server.rs:283-405, scaled up to a real
consensus protocol under loss + repeated leader kills).

Safety invariants asserted across seeds:
- election safety: at most one leader per term,
- durability: acknowledged writes survive leader crashes,
- log matching: all peers agree on the committed prefix,
- determinism: the whole chaos run is bit-identical per seed.
"""

import pickle
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

import madsim_tpu as ms
from madsim_tpu.net import Endpoint

import raft_kv
from raft_kv import (
    ClusterMonitor, N_PEERS, client_get, client_put, spawn_cluster,
)


def loss_config(rate: float = 0.05) -> ms.Config:
    cfg = ms.Config()
    cfg.net.packet_loss_rate = rate
    return cfg


def run_chaos(seed: int, n_puts: int = 6, kills: int = 2,
              loss: float = 0.05) -> dict:
    """Drive puts through the cluster while repeatedly killing the
    current leader; return the final cluster state for invariants."""
    monitor = ClusterMonitor()
    acked = {}

    async def main():
        h = ms.Handle.current()
        nodes = spawn_cluster(h, monitor)
        client = h.create_node().name("client").ip("10.0.9.9").build()

        async def run():
            ep = await Endpoint.bind("0.0.0.0:0")
            down = None
            for i in range(n_puts):
                await client_put(ep, f"k{i}", i)
                acked[f"k{i}"] = i
                if i < kills:
                    # kill the newest leader right after its ack
                    term = max(monitor.leaders_by_term)
                    (who,) = monitor.leaders_by_term[term]
                    if down is not None:
                        h.restart(nodes[down])
                    h.kill(nodes[who])
                    down = who
            if down is not None:
                h.restart(nodes[down])
            # quiesce so replication/commit indexes settle
            await ms.sleep(2.0)
            for k, v in acked.items():
                assert await client_get(ep, k) == v, (k, v)

        await client.spawn(run())

    ms.Runtime(seed=seed, config=loss_config(loss)).block_on(main())
    return {
        "leaders_by_term": {t: sorted(w)
                            for t, w in monitor.leaders_by_term.items()},
        "logs": {i: list(p.log) for i, p in monitor.peers.items()},
        "commits": {i: p.commit for i, p in monitor.peers.items()},
        "kvs": {i: dict(p.kv) for i, p in monitor.peers.items()},
        "acked": dict(acked),
    }


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_chaos_safety(seed):
    out = run_chaos(seed)
    # election safety: at most one leader per term
    for term, winners in out["leaders_by_term"].items():
        assert len(winners) == 1, (term, winners)
    # log matching: all peers agree on the shortest committed prefix
    min_commit = min(out["commits"].values())
    prefixes = {i: tuple(log[:min_commit])
                for i, log in out["logs"].items()}
    assert len(set(prefixes.values())) == 1, prefixes
    # durability: every acked write is in a majority of state machines
    for k, v in out["acked"].items():
        holders = sum(1 for kv in out["kvs"].values() if kv.get(k) == v)
        assert holders * 2 > N_PEERS, (k, v, out["kvs"])


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(100, 125))
def test_chaos_safety_soak(seed):
    """Wider seed soak for the full tier (the MADSIM_TEST_NUM analog
    at suite level)."""
    test_chaos_safety(seed)


def test_chaos_run_is_deterministic():
    a = run_chaos(11)
    b = run_chaos(11)
    assert a == b
    c = run_chaos(12)
    assert c["leaders_by_term"] != a["leaders_by_term"] or c["logs"] != a["logs"]


def test_killed_leader_recovers_from_fsynced_state():
    """The restarted node reloads (term, votedFor, log) from the
    simulated disk — its log prefix must already contain the entries
    committed before the crash (fs.py sync_all survives power-fail)."""
    monitor = ClusterMonitor()

    async def main():
        h = ms.Handle.current()
        nodes = spawn_cluster(h, monitor)
        client = h.create_node().name("client").ip("10.0.9.9").build()

        async def run():
            ep = await Endpoint.bind("0.0.0.0:0")
            for i in range(3):
                await client_put(ep, f"k{i}", i)
            term = max(monitor.leaders_by_term)
            (who,) = monitor.leaders_by_term[term]
            pre_crash_log = list(monitor.peers[who].log)
            pre_crash_commit = monitor.peers[who].commit
            h.kill(nodes[who])
            await client_put(ep, "after", 99)
            h.restart(nodes[who])
            await ms.sleep(2.0)
            revived = monitor.peers[who]  # re-registered on restart
            # every entry COMMITTED before the crash is still the prefix
            # of the revived node's log (uncommitted tail entries may
            # legitimately be replaced by the new leader)
            n = min(pre_crash_commit, revived.commit)
            assert tuple(revived.log[:n]) == tuple(pre_crash_log[:n])
            assert revived.kv.get("k0") == 0
            assert revived.kv.get("after") == 99

        await client.spawn(run())

    ms.Runtime(seed=3, config=loss_config(0.02)).block_on(main())


def test_example_main_runs():
    """The demo script itself (python examples/raft_kv.py) stays green."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "examples/raft_kv.py"],
        env={"MADSIM_TEST_SEED": "1", "PATH": "/usr/bin:/bin"},
        capture_output=True, text=True, timeout=120,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "election safety held" in proc.stdout


def test_membership_reconfiguration():
    """Single-server membership changes (Ongaro thesis §4.1-4.2): grow
    to 6, shrink away an original member, survive a leader kill in the
    new config, and keep every acked write."""
    import raft_kv
    from raft_kv import (
        client_add_server, client_remove_server, spawn_server,
    )

    monitor = raft_kv.ClusterMonitor()

    async def main():
        h = ms.Handle.current()
        nodes = {i: n for i, n in enumerate(spawn_cluster(h, monitor))}
        client = h.create_node().name("client").ip("10.0.9.9").build()

        async def run():
            ep = await Endpoint.bind("0.0.0.0:0")
            servers = list(range(N_PEERS))
            await client_put(ep, "pre", 1, servers=servers)

            # grow: bring up server 5, then commit the config change
            nodes[5] = spawn_server(h, monitor, 5)
            assert await client_add_server(ep, 5, servers=servers) == "ok"
            servers = [0, 1, 2, 3, 4, 5]
            await client_put(ep, "grown", 2, servers=servers)
            # the new server replicates the whole log
            await ms.sleep(1.0)
            assert monitor.peers[5].kv.get("pre") == 1

            # shrink: remove server 0 (kill it afterwards — a removed
            # server must not be needed for quorum)
            assert await client_remove_server(ep, 0, servers=servers) == "ok"
            h.kill(nodes[0])
            servers = [1, 2, 3, 4, 5]
            await client_put(ep, "shrunk", 3, servers=servers)

            # kill the current leader of the NEW config; cluster must
            # re-elect among {1..5} and keep all data
            term = max(monitor.leaders_by_term)
            (who,) = monitor.leaders_by_term[term]
            if who != 0:
                h.kill(nodes[who])
            await client_put(ep, "after-kill", 4, servers=servers)
            for k, v in [("pre", 1), ("grown", 2), ("shrunk", 3),
                         ("after-kill", 4)]:
                assert await client_get(ep, k, servers=servers) == v, k

            # config agreement: every live member sees {1,2,3,4,5}
            await ms.sleep(2.0)
            live = [i for i in servers if i != who]
            for i in live:
                assert monitor.peers[i].current_config() == frozenset(
                    {1, 2, 3, 4, 5}
                ), (i, monitor.peers[i].current_config())
            # election safety across the whole run
            for t, winners in monitor.leaders_by_term.items():
                assert len(winners) <= 1, (t, winners)

        await client.spawn(run())

    ms.Runtime(seed=8, config=loss_config(0.02)).block_on(main())


def test_removed_server_cannot_disrupt():
    """Leader stickiness (thesis §4.2.3): a removed server campaigning
    with ever-higher terms must not depose the working leader."""
    import raft_kv
    from raft_kv import client_remove_server

    monitor = raft_kv.ClusterMonitor()

    async def main():
        h = ms.Handle.current()
        spawn_cluster(h, monitor)
        client = h.create_node().name("client").ip("10.0.9.9").build()

        async def run():
            ep = await Endpoint.bind("0.0.0.0:0")
            await client_put(ep, "x", 1)
            assert await client_remove_server(ep, 4) == "ok"
            term_after_removal = max(monitor.leaders_by_term)
            # let the removed server (which stays running and will time
            # out, increment terms, and campaign) try to disrupt
            await ms.sleep(5.0)
            servers = [0, 1, 2, 3]
            # cluster still serves without a new election being forced
            # by the removed server
            assert await client_get(ep, "x", servers=servers) == 1
            later_terms = [t for t in monitor.leaders_by_term
                           if t > term_after_removal]
            # STABILITY, not just identity: in a loss-free run the
            # removed server's rising terms must trigger NO re-election
            # at all — the working leader stays (thesis §4.2.3)
            assert later_terms == [], monitor.leaders_by_term
            # ... and the stale server's terms really did rise (the
            # disruption attempt happened and was ignored)
            assert monitor.peers[4].term > term_after_removal

        await client.spawn(run())

    ms.Runtime(seed=5, config=loss_config(0.0)).block_on(main())


# ---- mutation sensitivity: the harness must CATCH protocol bugs ------
def _double_crash_schedule(seed, loss=0.05):
    """Deterministic chaos schedule killing TWO random nodes right
    after each acked write (the committing-majority amnesia scenario)."""
    monitor = ClusterMonitor()
    acked = {}

    async def main():
        import random

        h = ms.Handle.current()
        nodes = spawn_cluster(h, monitor)
        client = h.create_node().name("client").ip("10.0.9.9").build()

        async def run():
            ep = await Endpoint.bind("0.0.0.0:0")
            for i in range(3):
                try:
                    await client_put(ep, f"k{i}", i)
                    acked[f"k{i}"] = i
                except TimeoutError:
                    continue
                a, b = random.sample(range(N_PEERS), 2)
                h.kill(nodes[a])
                h.kill(nodes[b])
                await ms.sleep(random.uniform(0.05, 0.3))
                h.restart(nodes[a])
                h.restart(nodes[b])
                await ms.sleep(random.uniform(0.1, 0.5))
            await ms.sleep(1.5)

        await client.spawn(run())

    ms.Runtime(seed=seed, config=loss_config(loss)).block_on(main())
    lost = [
        k for k, v in acked.items()
        if sum(1 for p in monitor.peers.values() if p.kv.get(k) == v) * 2
        <= N_PEERS
    ]
    return monitor, acked, lost


# seeds where a 299-seed search showed the DISKLESS mutation losing an
# acked write under this schedule (deterministic, so pinned here)
_CATCHING_SEEDS = [35, 37, 50, 140, 213, 273]


def test_diskless_mutation_is_caught(monkeypatch):
    """Test-the-tests: strip raft's fsync persistence (the classic
    protocol bug — restart forgets term/votedFor/log) and the chaos
    schedules must DETECT it as an acked-write-durability violation.
    A DST harness whose invariants can't catch a seeded bug proves
    nothing; this pins the sensitivity."""
    async def no_save(self):
        pass

    async def no_load(self):
        pass

    monkeypatch.setattr(raft_kv.RaftPeer, "save", no_save)
    monkeypatch.setattr(raft_kv.RaftPeer, "load", no_load)
    caught = 0
    for seed in _CATCHING_SEEDS:
        _m, acked, lost = _double_crash_schedule(seed)
        caught += bool(lost)
    assert caught >= len(_CATCHING_SEEDS) - 1, (
        f"diskless raft escaped detection on {caught} pinned seeds"
    )


def test_durable_survives_the_catching_schedules():
    """The real (fsync-durable) implementation survives the exact
    schedules that break the diskless mutation — 0 violations across
    the full 299-seed search offline, re-checked here on the pinned
    catching seeds."""
    for seed in _CATCHING_SEEDS:
        monitor, acked, lost = _double_crash_schedule(seed)
        assert lost == [], (seed, lost)
        for term, w in monitor.leaders_by_term.items():
            assert len(w) == 1, (seed, term, w)
