"""madsim_tpu.chaos — nemesis fault plans, both execution modes.

Four layers under test: plan compilation (counter-based, per-seed
deterministic, vectorized), the new engine fault kinds (gray failure,
duplication, clock skew, one-way clog) and their identity defaults,
the search/shrink loop on the planted kvchaos lost-write bug (the
tier-1 smoke the evidence artifact scales up), and dual-mode parity —
the asyncio Nemesis plus the engine-vs-Recorder convergence check.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax

import madsim_tpu as ms
from madsim_tpu.chaos import (
    ClockSkew,
    CrashStorm,
    Duplicate,
    FaultEvent,
    FaultPlan,
    GrayFailure,
    LiteralPlan,
    Nemesis,
    Partition,
    PauseStorm,
    shrink_plan,
)
from madsim_tpu.check import election_safety, read_your_writes, stale_reads
from madsim_tpu.engine import (
    EngineConfig,
    search_seeds,
    make_init,
    make_run_while,
)
from madsim_tpu.engine.core import (
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_KILL,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_UNSLOW,
    pack_slow_arg,
)
from madsim_tpu.models import make_kvchaos, make_raft

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

SEEDS64 = np.arange(64, dtype=np.uint64)


# ------------------------------------------------------------- compilation
class TestPlanCompilation:
    def test_deterministic_and_per_seed_distinct(self):
        plan = FaultPlan((
            CrashStorm(targets=(1, 2, 3), n=2),
            GrayFailure(targets=(0, 1, 2, 3)),
            ClockSkew(targets=(0, 1)),
        ))
        a = plan.compile_batch(SEEDS64)
        b = plan.compile_batch(SEEDS64)
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(getattr(a, f), getattr(b, f)), f
        # distinct seeds draw distinct trajectories (overwhelmingly)
        assert len({tuple(map(tuple, a.args[s])) for s in range(64)}) > 32
        # and compile(seed) agrees with the batch row
        evs = plan.compile(7)
        assert [e.t for e in evs] == [int(t) for t, v in
                                      zip(a.time[7], a.valid[7]) if v]

    def test_windows_and_targets_respected(self):
        storm = CrashStorm(
            targets=(2, 5), n=3, t_min_ns=10, t_max_ns=20,
            down_min_ns=100, down_max_ns=200,
        )
        plan = FaultPlan((storm,))
        rows = plan.compile_batch(SEEDS64)
        kills = rows.kind == KIND_KILL
        assert (rows.time[kills] >= 10).all() and (rows.time[kills] < 20).all()
        assert np.isin(rows.args[..., 0][kills], (2, 5)).all()
        restarts = rows.kind == KIND_RESTART
        assert (rows.time[restarts] >= 110).all()
        assert (rows.time[restarts] < 220).all()

    def test_pause_storm_kinds(self):
        rows = FaultPlan((PauseStorm(targets=(0,), n=1),)).compile_batch(
            SEEDS64[:4]
        )
        assert set(rows.kind[rows.valid].tolist()) == {KIND_PAUSE, KIND_RESUME}

    def test_partition_edges_cross_the_cut(self):
        part = Partition(targets=(0, 1, 2, 3, 4))
        rows = FaultPlan((part,)).compile_batch(SEEDS64)
        for s in range(16):
            clogs = [
                (int(rows.args[s, j, 0]), int(rows.args[s, j, 1]))
                for j in range(rows.kind.shape[1])
                if rows.valid[s, j] and rows.kind[s, j] == KIND_CLOG
            ]
            assert clogs, "a nonempty proper cut always has edges"
            # the clogged edges must 2-color the nodes they touch
            side = {}
            for a, b in clogs:
                side.setdefault(a, 0)
                side[b] = 1 - side[a]
            for a, b in clogs:
                assert side[a] != side[b], (s, clogs)

    def test_asymmetric_partition_is_one_way(self):
        part = Partition(targets=(0, 1, 2), asymmetric=True)
        rows = FaultPlan((part,)).compile_batch(SEEDS64)
        assert (rows.kind[rows.valid] != KIND_CLOG).all()
        assert KIND_CLOG_1W in rows.kind[rows.valid]

    def test_plan_threefry_matches_engine_generator(self):
        # chaos/plan.py carries an array-form copy of the cipher; plan
        # draws never enter the trace hash, so textual drift from the
        # engine's generator would otherwise be silent — pin them equal
        from madsim_tpu.chaos.plan import _vthreefry
        from madsim_tpu.engine import np_threefry2x32

        rng = np.random.default_rng(0)
        cases = rng.integers(0, 2**32, size=(64, 4), dtype=np.uint64)
        for k0, k1, x0, x1 in cases:
            a0, a1 = np_threefry2x32(
                np.uint32(k0), np.uint32(k1), np.uint32(x0), np.uint32(x1)
            )
            b0, b1 = _vthreefry(
                np.uint32(k0), np.uint32(k1), np.uint32(x0), np.uint32(x1)
            )
            assert (int(a0), int(a1)) == (int(b0), int(b1))
        # and the vectorized path equals the scalar loop
        v0, v1 = _vthreefry(
            cases[:, 0].astype(np.uint32), cases[:, 1].astype(np.uint32),
            cases[:, 2].astype(np.uint32), cases[:, 3].astype(np.uint32),
        )
        for i, (k0, k1, x0, x1) in enumerate(cases):
            a0, a1 = np_threefry2x32(
                np.uint32(k0), np.uint32(k1), np.uint32(x0), np.uint32(x1)
            )
            assert (int(v0[i]), int(v1[i])) == (int(a0), int(a1))

    def test_window_span_must_fit_uint32(self):
        with pytest.raises(ValueError, match="does not fit uint32"):
            CrashStorm(targets=(1,), t_min_ns=0, t_max_ns=5_000_000_000)

    def test_plan_hash_covers_specs(self):
        p1 = FaultPlan((CrashStorm(targets=(1,), n=1),))
        p2 = FaultPlan((CrashStorm(targets=(1,), n=2),))
        assert p1.hash() != p2.hash()
        assert p1.hash() == FaultPlan((CrashStorm(targets=(1,), n=1),)).hash()

    def test_target_validation_against_workload(self):
        wl = make_raft()
        with pytest.raises(ValueError, match="targets node 9"):
            FaultPlan((CrashStorm(targets=(9,)),)).compile_batch(
                SEEDS64[:2], wl=wl
            )

    def test_literal_plan_mask(self):
        lp = LiteralPlan(
            events=(
                FaultEvent(10, KIND_KILL, 1),
                FaultEvent(20, KIND_RESTART, 1),
            ),
            enabled=(False, True),
        )
        assert [e.kind for e in lp.compile(0)] == [KIND_RESTART]
        rows = lp.compile_batch(SEEDS64[:3])
        assert rows.valid.tolist() == [[False, True]] * 3


# ------------------------------------------------------- engine fault kinds
@pytest.fixture(scope="module")
def kv_plain():
    return make_kvchaos(writes=5, chaos=False)


@pytest.fixture(scope="module")
def kv_cfg():
    return EngineConfig(pool_size=96, loss_p=0.02)


class TestEngineFaultKinds:
    def test_gray_failure_slows_completion(self, kv_plain, kv_cfg):
        seeds = SEEDS64
        init = make_init(kv_plain, kv_cfg)
        run = jax.jit(make_run_while(kv_plain, kv_cfg, 4000))
        base = run(init(seeds))
        gray = FaultPlan((GrayFailure(
            targets=(0, 1, 2, 3, 4, 5), n_links=6,
            t_min_ns=1_000_000, t_max_ns=5_000_000,
            dur_min_ns=400_000_000, dur_max_ns=500_000_000,
            mult_min=32, mult_max=64,
        ),))
        init_g = make_init(kv_plain, kv_cfg, plan_slots=gray.slots)
        slowed = run(init_g(seeds, gray.compile_batch(seeds, wl=kv_plain)))
        assert np.asarray(base.halted).all()
        assert np.asarray(slowed.halted).all()
        assert (
            np.median(np.asarray(slowed.halt_time))
            > 2 * np.median(np.asarray(base.halt_time))
        )

    def test_duplication_multiplies_traffic_and_identity_when_off(
        self, kv_plain, kv_cfg
    ):
        seeds = SEEDS64
        init = make_init(kv_plain, kv_cfg)
        run_d = jax.jit(make_run_while(kv_plain, kv_cfg, 4000, dup_rows=True))
        run = jax.jit(make_run_while(kv_plain, kv_cfg, 4000))
        base = run(init(seeds))
        # dup_rows compiled but no plan: values bit-identical
        same = run_d(init(seeds))
        assert np.array_equal(np.asarray(same.trace), np.asarray(base.trace))
        assert np.array_equal(
            np.asarray(same.node_state), np.asarray(base.node_state)
        )
        dupp = FaultPlan((Duplicate(
            t_min_ns=0, t_max_ns=1,
            dur_min_ns=500_000_000, dur_max_ns=600_000_000,
        ),))
        init_d = make_init(kv_plain, kv_cfg, plan_slots=dupp.slots)
        dup = run_d(init_d(seeds, dupp.compile_batch(seeds, wl=kv_plain)))
        assert np.asarray(dup.halted).all()
        assert (
            int(np.asarray(dup.msg_count).sum())
            > 2 * int(np.asarray(base.msg_count).sum())
        )

    def test_clock_skew_is_observed_by_handlers(self):
        import jax.numpy as jnp

        from madsim_tpu.engine import Workload, user_kind

        def on_init(ctx):
            eb = ctx.emits()
            eb.after(10_000_000, user_kind(1), 0)
            return ctx.state, eb.build()

        def on_probe(ctx):
            # store the observed clock in ms
            new = ctx.state.at[0].set(
                (ctx.now // jnp.int64(1_000_000)).astype(jnp.int32)
            )
            eb = ctx.emits()
            eb.halt()
            return new, eb.build()

        wl = Workload(
            name="skew-probe", n_nodes=1, state_width=1,
            handlers=(on_init, on_probe), max_emits=2,
            delay_bound_ns=20_000_000,
        )
        cfg = EngineConfig(pool_size=8)
        seeds = np.arange(8, dtype=np.uint64)
        skew = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SKEW, a0=0, a1=500_000_000),
        ))
        run = jax.jit(make_run_while(wl, cfg, 50))
        plain = run(make_init(wl, cfg)(seeds))
        init_s = make_init(wl, cfg, plan_slots=1)
        skewed = run(init_s(seeds, skew.compile_batch(seeds)))
        d = np.asarray(skewed.node_state)[:, 0, 0] - np.asarray(
            plain.node_state
        )[:, 0, 0]
        assert (d == 500).all()
        # skew shifts the handler's VIEW only: the true-time halt clock
        # moves by at most the per-step poll-cost noise the extra plan
        # event introduces (shifted step coordinates), never by the
        # half-second the handlers observed
        dt = np.abs(
            np.asarray(skewed.halt_time) - np.asarray(plain.halt_time)
        )
        assert (dt < 10_000).all()

    def test_one_way_clog_sets_one_direction(self, kv_plain, kv_cfg):
        seeds = SEEDS64[:4]
        lp = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_CLOG_1W, a0=2, a1=3),
        ))
        init_1 = make_init(kv_plain, kv_cfg, plan_slots=1)
        run = jax.jit(make_run_while(kv_plain, kv_cfg, 200))
        out = run(init_1(seeds, lp.compile_batch(seeds)))
        clog = np.asarray(out.clog)
        assert clog[:, 2, 3].all() and not clog[:, 3, 2].any()

    def test_slow_link_args_roundtrip(self):
        packed = pack_slow_arg(3, 17)
        assert (packed & 0xFF) - 1 == 3 and packed >> 8 == 17
        assert (pack_slow_arg(-1, 9) & 0xFF) == 0

    def test_pool_too_small_for_plan_rows(self, kv_plain):
        cfg = EngineConfig(pool_size=8)
        with pytest.raises(ValueError, match="fault-plan rows"):
            make_init(kv_plain, cfg, plan_slots=6)


# -------------------------------------------- search + shrink (planted bug)
@pytest.fixture(scope="module")
def kv_bug():
    return make_kvchaos(writes=5, record=True, bug=True, chaos=False)


@pytest.fixture(scope="module")
def nemesis_plan():
    return FaultPlan((
        CrashStorm(
            targets=(1, 2, 3, 4), n=2,
            t_min_ns=20_000_000, t_max_ns=400_000_000,
            down_min_ns=50_000_000, down_max_ns=300_000_000,
        ),
    ), name="kv-nemesis")


def _kv_hinv(box):
    def inv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


class TestSearchAndShrink:
    def test_nemesis_search_finds_planted_bug_and_shrinks(
        self, kv_bug, kv_cfg, nemesis_plan
    ):
        """The tier-1 smoke of the whole loop: a plan-driven sweep digs
        out the kvchaos lost-write mutant, ddmin shrinks the fault
        schedule to <= 4 events, and the shrunk (seed, config, plan)
        replays to the identical violation and trace hash."""
        box = {}
        rep = search_seeds(
            kv_bug, kv_cfg, None, n_seeds=256, max_steps=3000,
            history_invariant=_kv_hinv(box), plan=nemesis_plan,
        )
        assert rep.failing_seeds.size > 0, "nemesis must trigger the bug"
        assert rep.overflowed_seeds.size == 0
        assert rep.plan_hash == nemesis_plan.hash()
        assert f"plan_hash={nemesis_plan.hash()}" in rep.banner()

        # some seeds genuinely need the whole storm; at least one of the
        # first few must shrink strictly below the full plan
        results = [
            shrink_plan(
                kv_bug, kv_cfg, int(s), nemesis_plan,
                history_invariant=_kv_hinv({}), max_steps=3000,
            )
            for s in rep.failing_seeds[:3]
        ]
        assert all(len(r.events) <= 4 for r in results)
        res = min(results, key=lambda r: len(r.events))
        assert len(res.events) < res.original_events
        bad = res.seed

        # exact replay: same violating seed, same trace hash
        box2 = {}
        rep2 = search_seeds(
            kv_bug, kv_cfg, None, n_seeds=1, max_steps=3000,
            seed_base=bad, history_invariant=_kv_hinv(box2), plan=res.plan,
        )
        assert rep2.failing_seeds.tolist() == [bad]
        assert int(rep2.traces[0]) == res.trace

    def test_clean_model_is_clean_under_the_same_plan(
        self, kv_cfg, nemesis_plan
    ):
        clean = make_kvchaos(writes=5, record=True, chaos=False)
        box = {}
        rep = search_seeds(
            clean, kv_cfg, None, n_seeds=256, max_steps=3000,
            history_invariant=_kv_hinv(box), plan=nemesis_plan,
        )
        assert rep.failing_seeds.size == 0
        assert rep.unhalted_seeds.size == 0

    def test_shrink_rejects_non_failing_seed(self, kv_bug, kv_cfg, nemesis_plan):
        box = {}
        rep = search_seeds(
            kv_bug, kv_cfg, None, n_seeds=64, max_steps=3000,
            history_invariant=_kv_hinv(box), plan=nemesis_plan,
        )
        passing = sorted(set(range(64)) - set(rep.failing_seeds.tolist()))
        with pytest.raises(ValueError, match="does not fail"):
            shrink_plan(
                kv_bug, kv_cfg, passing[0], nemesis_plan,
                history_invariant=_kv_hinv({}), max_steps=3000,
            )


# ----------------------------------------------------- asyncio mode parity
class TestNemesisAsyncio:
    def test_nemesis_applies_plan_events(self):
        plan = LiteralPlan(events=(
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=1),
            FaultEvent(t=150_000_000, kind=KIND_RESTART, a0=1),
            FaultEvent(t=10_000_000, kind=KIND_SKEW, a0=0, a1=250_000_000),
            FaultEvent(t=20_000_000, kind=KIND_SLOW_LINK, a0=0,
                       a1=pack_slow_arg(1, 8)),
            FaultEvent(t=30_000_000, kind=KIND_DUP_ON),
            FaultEvent(t=170_000_000, kind=KIND_DUP_OFF),
        ))
        rt = ms.Runtime(seed=7)
        n0 = rt.create_node().name("n0").build()
        n1 = rt.create_node().name("n1").build()

        async def main():
            from madsim_tpu.net.netsim import NetSim
            from madsim_tpu.runtime.time_ import SystemTime

            h = ms.Handle.current()
            nem = Nemesis(plan, nodes=[n0, n1])
            wall = []

            async def probe():
                base = h.time.base_unix_ns
                for _ in range(3):
                    await ms.sleep(0.06)
                    wall.append(SystemTime.now().unix_ns - base - ms.now_ns())

            p = n0.spawn(probe())
            applied = await nem.run()
            await p
            netsim = h.simulator(NetSim)
            return applied, wall, netsim

        rt.set_time_limit(2.0)
        applied, wall, netsim = rt.block_on(main())
        # events applied in time order, at their plan times
        times = [t for t, _ in applied]
        assert times == sorted(times)
        assert [e.kind for _, e in applied] == [
            KIND_SKEW, KIND_SLOW_LINK, KIND_DUP_ON, KIND_KILL,
            KIND_RESTART, KIND_DUP_OFF,
        ]
        # skew visible to the node's wall clock
        assert wall == [250_000_000] * 3
        # slow link installed both directions, dup flag back off
        assert netsim.network.slow_mult(n0.id, n1.id) == 8
        assert netsim.network.slow_mult(n1.id, n0.id) == 8
        assert netsim._duplicate is False

    def test_default_mapping_targets_created_nodes(self):
        # plan node i defaults to the i-th CREATED node (ids from 1;
        # id 0 is the unkillable main supervisor node)
        plan = LiteralPlan(events=(
            FaultEvent(t=1_000_000, kind=KIND_KILL, a0=0),
        ))
        rt = ms.Runtime(seed=2)
        n0 = rt.create_node().name("victim").build()

        async def main():
            # the pre-kill NodeInfo: _retire marks it killed and swaps
            # in a fresh incarnation under the same id
            info = ms.Handle.current().executor.nodes[n0.id]
            await Nemesis(plan).run()
            return info

        info = rt.block_on(main())
        assert info.killed

    def test_default_mapping_rejects_out_of_range_target(self):
        plan = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_KILL, a0=3),
        ))
        rt = ms.Runtime(seed=2)
        rt.create_node().build()

        async def main():
            await Nemesis(plan).run()

        with pytest.raises(ValueError, match="nodes="):
            rt.block_on(main())

    def test_nemesis_same_trajectory_as_engine_compile(self):
        plan = FaultPlan((CrashStorm(targets=(0, 1), n=2),))
        rt = ms.Runtime(seed=11)
        rt.create_node().build()
        rt.create_node().build()

        async def main():
            nem = Nemesis(plan, nodes=[1, 2])
            return nem.events()

        events = rt.block_on(main())
        # the asyncio nemesis drives EXACTLY the events the batched
        # engine would pre-seed for the same seed (dual-mode parity)
        assert events == sorted(plan.compile(11), key=lambda e: e.t)

    def test_node_wide_slow_overwrites_like_the_engine(self):
        # engine parity: node-wide slow/unslow OVERWRITES every link
        # touching the node — a node-wide heal also wipes an earlier
        # link-specific multiplier (the (N,N) matrix semantics)
        rt = ms.Runtime(seed=1)
        a = rt.create_node().build()
        b = rt.create_node().build()

        async def main():
            from madsim_tpu.net.netsim import NetSim

            net = ms.Handle.current().simulator(NetSim)
            net.slow_link(a, b, 4)
            net.slow_node(a, 8)
            assert net.network.slow_mult(a.id, b.id) == 8
            net.slow_node(a, 1)
            assert net.network.slow_mult(a.id, b.id) == 1

        rt.block_on(main())

    def test_duplication_duplicates_datagrams(self):
        rt = ms.Runtime(seed=3)
        a = rt.create_node().name("a").ip("10.0.0.1").build()
        b = rt.create_node().name("b").ip("10.0.0.2").build()

        async def main():
            from madsim_tpu.net import Endpoint
            from madsim_tpu.net.netsim import NetSim

            h = ms.Handle.current()
            got = []

            async def server():
                ep = await Endpoint.bind("0.0.0.0:700")
                while True:
                    msg, _ = await ep.recv_from(1)
                    got.append(msg)

            async def client():
                ep = await Endpoint.bind("0.0.0.0:0")
                h.simulator(NetSim).set_duplicate(True)
                await ep.send_to("10.0.0.2:700", 1, "x")
                await ms.sleep(0.5)
                h.simulator(NetSim).set_duplicate(False)
                await ep.send_to("10.0.0.2:700", 1, "y")
                await ms.sleep(0.5)

            b.spawn(server())
            await a.spawn(client())
            return got

        rt.set_time_limit(5.0)
        got = rt.block_on(main())
        assert got.count("x") == 2 and got.count("y") == 1


# ---------------------------------------- dual-mode convergence (satellite)
class TestDualModeConvergence:
    def test_raft_verdicts_converge_across_modes(self, monkeypatch):
        """The same raft protocol, one seed, both execution modes: the
        batched engine's recorded election history and the asyncio
        runtime's Recorder history must produce identical
        election-safety verdicts."""
        import raft_kv
        from madsim_tpu.check import Recorder
        from madsim_tpu.models.raft import OP_ELECT

        seeds = [1, 2, 3]  # consecutive: the engine sweep runs seed_base..+n

        # engine mode: recorded wins through search_seeds
        box = {}

        def inv(h):
            box["ok"] = election_safety(h, elect_op=OP_ELECT)
            return box["ok"]

        search_seeds(
            make_raft(record=True), EngineConfig(pool_size=48, loss_p=0.02),
            None, n_seeds=len(seeds), seed_base=seeds[0], max_steps=600,
            history_invariant=inv,
        )
        # seeds are consecutive from seeds[0]; pick our three
        engine_verdicts = [bool(box["ok"][s - seeds[0]]) for s in seeds]

        # asyncio mode: the raft_kv example cluster with a Recorder spy
        # on election wins
        async def no_save(self):
            return None

        async def no_load(self):
            return None

        monkeypatch.setattr(raft_kv.RaftPeer, "save", no_save)
        monkeypatch.setattr(raft_kv.RaftPeer, "load", no_load)
        orig_note = raft_kv.ClusterMonitor.note_leader
        asyncio_verdicts = []
        for seed in seeds:
            rec = Recorder()

            def spy(self, term, who, rec=rec):
                rec.event(client=who, op=OP_ELECT, key=term, arg=who)
                orig_note(self, term, who)

            monkeypatch.setattr(raft_kv.ClusterMonitor, "note_leader", spy)
            monitor = raft_kv.ClusterMonitor()

            async def main():
                h = ms.Handle.current()
                raft_kv.spawn_cluster(h, monitor)
                await ms.sleep(2.0)

            cfg = ms.Config()
            cfg.net.packet_loss_rate = 0.02
            ms.Runtime(seed=seed, config=cfg).block_on(main())
            assert len(rec) > 0, "the cluster must elect at least once"
            asyncio_verdicts.append(
                bool(election_safety(rec.to_batch(), elect_op=OP_ELECT)[0])
            )

        assert engine_verdicts == asyncio_verdicts == [True] * len(seeds)
