""".proto service codegen (madsim-tonic-build parity, C23)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.services import grpc
from madsim_tpu.services.grpc_codegen import compile_proto, compile_proto_source


def run(seed, coro_fn):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(60)
    return rt.block_on(coro_fn())


NS = compile_proto("examples/proto/helloworld.proto")


class Greeter(NS.GreeterServicer):
    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(3):
            yield {"message": f"#{i}"}

    async def lots_of_greetings(self, stream):
        names = [m["name"] async for m in stream]
        return {"message": ", ".join(names)}

    async def bidi_hello(self, stream):
        async for m in stream:
            yield {"message": f"ack:{m['name']}"}


def test_parses_services_and_shapes():
    assert NS.GreeterServicer.SERVICE_NAME == "helloworld.Greeter"
    assert NS.GreeterServicer.say_hello.__rpc_shape__ == "unary"
    assert NS.GreeterServicer.lots_of_replies.__rpc_shape__ == "server_stream"
    assert NS.GreeterServicer.lots_of_greetings.__rpc_shape__ == "client_stream"
    assert NS.GreeterServicer.bidi_hello.__rpc_shape__ == "bidi"


def test_generated_client_and_servicer_end_to_end():
    async def main():
        h = ms.Handle.current()

        async def serve():
            await grpc.Server.builder().add_service(Greeter()).serve(
                "0.0.0.0:50051"
            )

        h.create_node().name("srv").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect("10.0.0.1:50051")
            c = NS.GreeterClient(ch)
            r = await c.say_hello({"name": "world"})
            assert r == {"message": "Hello world!"}
            stream = await c.lots_of_replies({"name": "x"})
            assert [m["message"] async for m in stream] == ["#0", "#1", "#2"]
            tx, reply = await c.lots_of_greetings()
            await tx.send({"name": "a"})
            await tx.send({"name": "b"})
            await tx.finish()
            assert (await reply) == {"message": "a, b"}
            tx, stream = await c.bidi_hello()
            await tx.send({"name": "z"})
            assert (await stream.message())["message"] == "ack:z"
            await tx.finish()
            return True

        return await cli.spawn(client())

    assert run(31, main)


def test_unoverridden_method_is_unimplemented():
    class Partial(NS.GreeterServicer):
        async def say_hello(self, request):
            return {"message": "only this one"}

    async def main():
        h = ms.Handle.current()

        async def serve():
            await grpc.Server.builder().add_service(Partial()).serve(
                "0.0.0.0:50051"
            )

        h.create_node().name("srv").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect("10.0.0.1:50051")
            c = NS.GreeterClient(ch)
            assert (await c.say_hello({"name": "x"}))["message"] == "only this one"
            with pytest.raises(grpc.Status) as ei:
                await c.say_hello.__self__.channel.unary(
                    "/helloworld.Greeter/lots_of_greetings", None
                )
            # unimplemented default for the client-stream method
            assert ei.value.code == grpc.Code.UNIMPLEMENTED
            return True

        return await cli.spawn(client())

    assert run(32, main)


def test_source_parsing_details():
    ns = compile_proto_source(
        """
        // comment with rpc Fake (A) returns (B);
        package a.b;
        service S {
          rpc DoThing (X) returns (stream Y); /* inline */
        }
        """
    )
    assert ns.SServicer.SERVICE_NAME == "a.b.S"
    assert ns.SServicer.do_thing.__rpc_shape__ == "server_stream"
    assert not hasattr(ns, "FakeServicer")


# ---------------------------------------------------------------------------
# message codegen (prost.rs:326-330 parity: typed messages + sim stubs)
# ---------------------------------------------------------------------------


def test_generates_message_dataclasses():
    assert NS.HelloRequest(name="x").name == "x"
    assert NS.HelloRequest().name == ""  # proto3 zero value
    assert NS.HelloReply.__proto_fields__ == (
        ("message", 1, "singular", "string"),
    )


TYPED_SRC = """
syntax = "proto3";
package shop;

enum Status {
  STATUS_UNKNOWN = 0;
  STATUS_PAID = 1;
  STATUS_SHIPPED = 2;
}

message Item {
  string sku = 1;
  uint32 count = 2;
  repeated string tags = 3;
}

message Order {
  uint64 id = 1;
  Status status = 2;
  repeated Item items = 3;
  map<string, int64> totals = 4;
  message Address { string city = 1; }
  Address ship_to = 5;
  oneof payment {
    string card = 6;
    string invoice = 7;
  }
}

service Orders {
  rpc Place (Order) returns (Order);
}
"""


def test_typed_messages_full_surface():
    ns = compile_proto_source(TYPED_SRC)
    assert ns.Status.STATUS_PAID == 1
    item = ns.Item(sku="a-1", count=2, tags=["x"])
    assert item.count == 2 and item.tags == ["x"]
    order = ns.Order(id=7, status=ns.Status.STATUS_PAID, items=[item])
    assert order.totals == {}  # map default
    assert order.ship_to is None  # message field default
    assert order.card == ""  # oneof members are plain fields
    # nested message compiled under Outer_Inner
    addr = ns.Order_Address(city="Zurich")
    order.ship_to = addr
    nums = {f[0]: f[1] for f in ns.Order.__proto_fields__}
    assert nums == {
        "id": 1, "status": 2, "items": 3, "totals": 4, "ship_to": 5,
        "card": 6, "invoice": 7,
    }


def test_typed_messages_pickle_roundtrip():
    import pickle

    ns = compile_proto_source(TYPED_SRC)
    order = ns.Order(
        id=9,
        status=ns.Status.STATUS_SHIPPED,
        items=[ns.Item(sku="s", count=1)],
        totals={"chf": 42},
        ship_to=ns.Order_Address(city="Bern"),
    )
    back = pickle.loads(pickle.dumps(order))
    assert back.id == 9 and back.status == 2
    assert back.items[0].sku == "s"  # nested message, not a dict
    assert isinstance(back.items[0], ns.Item)
    assert back.ship_to.city == "Bern"
    assert back.totals == {"chf": 42}


class TypedGreeter(NS.GreeterServicer):
    async def say_hello(self, request):
        # typed request in, typed reply out
        return NS.HelloReply(message=f"Hello {request.message.name}!")


def test_typed_messages_through_sim_grpc():
    async def main():
        h = ms.Handle.current()
        node = h.create_node().name("server").ip("10.9.0.1").build()

        async def serve():
            await grpc.Server.builder().add_service(TypedGreeter()).serve(
                "10.9.0.1:50051"
            )

        node.spawn(serve())
        cli = h.create_node().name("cli").ip("10.9.0.2").build()

        async def go():
            await ms.sleep(0.1)
            ch = await grpc.connect("10.9.0.1:50051")
            c = NS.GreeterClient(ch)
            r = await c.say_hello(NS.HelloRequest(name="typed"))
            assert isinstance(r, NS.HelloReply)
            return r.message

        return await cli.spawn(go())

    assert run(5, main) == "Hello typed!"


def test_keyword_field_names_are_escaped():
    # 'from' etc. can't be dataclass fields; generated code suffixes
    # them (prost escapes r#from) while __proto_fields__ keeps the wire
    # name
    ns = compile_proto_source(
        "message Transfer { string from = 1; string to = 2; bool in = 3; }"
    )
    t = ns.Transfer(from_="a", to="b", in_=True)
    assert t.from_ == "a" and t.in_ is True
    names = [f[0] for f in ns.Transfer.__proto_fields__]
    assert names == ["from", "to", "in"]


def test_nested_message_class_names_qualified():
    ns = compile_proto_source(TYPED_SRC)
    assert ns.Order_Address.__name__ == "Order_Address"
    assert ns.Order.__name__ == "Order"
