""".proto service codegen (madsim-tonic-build parity, C23)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.services import grpc
from madsim_tpu.services.grpc_codegen import compile_proto, compile_proto_source


def run(seed, coro_fn):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(60)
    return rt.block_on(coro_fn())


NS = compile_proto("examples/proto/helloworld.proto")


class Greeter(NS.GreeterServicer):
    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(3):
            yield {"message": f"#{i}"}

    async def lots_of_greetings(self, stream):
        names = [m["name"] async for m in stream]
        return {"message": ", ".join(names)}

    async def bidi_hello(self, stream):
        async for m in stream:
            yield {"message": f"ack:{m['name']}"}


def test_parses_services_and_shapes():
    assert NS.GreeterServicer.SERVICE_NAME == "helloworld.Greeter"
    assert NS.GreeterServicer.say_hello.__rpc_shape__ == "unary"
    assert NS.GreeterServicer.lots_of_replies.__rpc_shape__ == "server_stream"
    assert NS.GreeterServicer.lots_of_greetings.__rpc_shape__ == "client_stream"
    assert NS.GreeterServicer.bidi_hello.__rpc_shape__ == "bidi"


def test_generated_client_and_servicer_end_to_end():
    async def main():
        h = ms.Handle.current()

        async def serve():
            await grpc.Server.builder().add_service(Greeter()).serve(
                "0.0.0.0:50051"
            )

        h.create_node().name("srv").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect("10.0.0.1:50051")
            c = NS.GreeterClient(ch)
            r = await c.say_hello({"name": "world"})
            assert r == {"message": "Hello world!"}
            stream = await c.lots_of_replies({"name": "x"})
            assert [m["message"] async for m in stream] == ["#0", "#1", "#2"]
            tx, reply = await c.lots_of_greetings()
            await tx.send({"name": "a"})
            await tx.send({"name": "b"})
            await tx.finish()
            assert (await reply) == {"message": "a, b"}
            tx, stream = await c.bidi_hello()
            await tx.send({"name": "z"})
            assert (await stream.message())["message"] == "ack:z"
            await tx.finish()
            return True

        return await cli.spawn(client())

    assert run(31, main)


def test_unoverridden_method_is_unimplemented():
    class Partial(NS.GreeterServicer):
        async def say_hello(self, request):
            return {"message": "only this one"}

    async def main():
        h = ms.Handle.current()

        async def serve():
            await grpc.Server.builder().add_service(Partial()).serve(
                "0.0.0.0:50051"
            )

        h.create_node().name("srv").ip("10.0.0.1").init(serve).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ch = await grpc.connect("10.0.0.1:50051")
            c = NS.GreeterClient(ch)
            assert (await c.say_hello({"name": "x"}))["message"] == "only this one"
            with pytest.raises(grpc.Status) as ei:
                await c.say_hello.__self__.channel.unary(
                    "/helloworld.Greeter/lots_of_greetings", None
                )
            # unimplemented default for the client-stream method
            assert ei.value.code == grpc.Code.UNIMPLEMENTED
            return True

        return await cli.spawn(client())

    assert run(32, main)


def test_source_parsing_details():
    ns = compile_proto_source(
        """
        // comment with rpc Fake (A) returns (B);
        package a.b;
        service S {
          rpc DoThing (X) returns (stream Y); /* inline */
        }
        """
    )
    assert ns.SServicer.SERVICE_NAME == "a.b.S"
    assert ns.SServicer.do_thing.__rpc_shape__ == "server_stream"
    assert not hasattr(ns, "FakeServicer")
