"""@service/@rpc decorator (the #[madsim::service] macro analog,
madsim-macros/src/service.rs:61-110)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import Endpoint
from madsim_tpu.net.service import rpc, service


class Get:
    def __init__(self, key):
        self.key = key


class Put:
    def __init__(self, key, value):
        self.key = key
        self.value = value


@service
class KvStore:
    def __init__(self):
        self.data = {}

    @rpc
    async def get(self, req: Get):
        return self.data.get(req.key)

    @rpc
    async def put(self, req: Put):
        old = self.data.get(req.key)
        self.data[req.key] = req.value
        return old


def run(seed, coro_fn):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(60)
    return rt.block_on(coro_fn())


def test_service_serves_rpc_methods():
    async def main():
        h = ms.Handle.current()

        async def server():
            await KvStore().serve("0.0.0.0:7000")

        h.create_node().name("srv").ip("10.0.0.1").init(server).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ep = await Endpoint.bind("0.0.0.0:0")
            assert await ep.call("10.0.0.1:7000", Put("a", 1)) is None
            assert await ep.call("10.0.0.1:7000", Get("a")) == 1
            assert await ep.call("10.0.0.1:7000", Put("a", 2)) == 1
            return True

        return await cli.spawn(client())

    assert run(5, main)


def test_service_requires_annotations_and_methods():
    with pytest.raises(TypeError, match="must annotate"):

        @service
        class Bad:
            @rpc
            async def get(self, req):
                return None

    with pytest.raises(TypeError, match="no @rpc methods"):

        @service
        class Empty:
            async def not_rpc(self):
                return None


def test_serve_on_shared_endpoint():
    """Two services multiplexed on one endpoint via serve_on."""

    class Ping:
        pass

    # classes must be distinct request types
    class Pong:
        pass

    @service
    class A:
        @rpc
        async def ping(self, req: Ping):
            return "A"

    @service
    class B:
        @rpc
        async def pong(self, req: Pong):
            return "B"

    async def main():
        h = ms.Handle.current()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:7000")
            await A().serve_on(ep)
            await B().serve_on(ep)

        h.create_node().name("srv").ip("10.0.0.1").init(server).build()
        cli = h.create_node().name("cli").ip("10.0.0.2").build()

        async def client():
            await ms.sleep(0.1)
            ep = await Endpoint.bind("0.0.0.0:0")
            assert await ep.call("10.0.0.1:7000", Ping()) == "A"
            assert await ep.call("10.0.0.1:7000", Pong()) == "B"
            return True

        return await cli.spawn(client())

    assert run(6, main)
