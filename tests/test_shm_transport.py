"""Shared-memory fast-path transport (native/shm_transport.cpp) — the
kernel-bypass-class endpoint filling the reference's UCX/eRPC role
(std/net/ucx.rs:23-30, erpc.rs:24-30)."""

import asyncio
import shutil
import time

import pytest

from madsim_tpu.std import fastpath

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)


def run(coro):
    return asyncio.run(coro)


def test_shm_roundtrip():
    async def main():
        a = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        b = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        try:
            await a.send_to(("127.0.0.1", b.local_addr[1]), 5, {"x": [1, 2, 3]})
            payload, src = await b.recv_from(5, timeout=5)
            assert payload == {"x": [1, 2, 3]}
            await b.send_to(src, 6, "pong")
            payload2, _ = await a.recv_from(6, timeout=5)
            assert payload2 == "pong"
        finally:
            a.close()
            b.close()

    run(main())


def test_shm_recv_timeout_and_refused():
    async def main():
        a = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        try:
            with pytest.raises(asyncio.TimeoutError):
                await a.recv_from(1, timeout=0.2)
            with pytest.raises(ConnectionError):
                await a.send_to(("127.0.0.1", 1), 1, "nobody home")
        finally:
            a.close()

    run(main())


def test_shm_many_messages_ordered_per_tag():
    async def main():
        a = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        b = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        try:
            for i in range(200):
                await a.send_to(("127.0.0.1", b.local_addr[1]), 1, i)
            got = [(await b.recv_from(1, timeout=5))[0] for _ in range(200)]
            assert got == list(range(200))
        finally:
            a.close()
            b.close()

    run(main())


def test_shm_large_payload():
    async def main():
        a = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        b = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        blob = bytes(range(256)) * 4096  # 1 MiB
        try:
            await a.send_to(("127.0.0.1", b.local_addr[1]), 2, blob)
            payload, _ = await b.recv_from(2, timeout=10)
            assert payload == blob
        finally:
            a.close()
            b.close()

    run(main())


def test_shm_backpressure_does_not_deadlock():
    """Two endpoints flooding each other: queued sends + the drain
    thread keep both sides moving (the failure mode the epoll transport
    guards against with EPOLLOUT queues)."""

    async def main():
        a = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        b = await fastpath.ShmEndpoint.bind("127.0.0.1:0")
        blob = b"z" * 65536
        n = 100
        try:
            async def flood(src, dst):
                for _ in range(n):
                    await src.send_to(("127.0.0.1", dst.local_addr[1]), 3, blob)

            async def drain(ep):
                for _ in range(n):
                    await ep.recv_from(3, timeout=30)

            await asyncio.wait_for(
                asyncio.gather(flood(a, b), flood(b, a), drain(a), drain(b)),
                timeout=60,
            )
        finally:
            a.close()
            b.close()

    run(main())


def test_pick_endpoint_prefers_shm_on_loopback():
    async def main():
        ep = await fastpath.pick_endpoint("127.0.0.1:0")
        try:
            assert isinstance(ep, fastpath.ShmEndpoint)
        finally:
            ep.close()

    run(main())


def _raw_pingpong_rtt(mod, prefix: str, n: int = 1500) -> float:
    """Transport-level ping-pong RTT via the C ABI directly (the asyncio
    wrapper's thread-pool hop costs ~90 us and would drown the
    comparison)."""
    import ctypes

    lib = mod._load()
    bind = getattr(lib, prefix + "bind")
    send = getattr(lib, prefix + "send")
    recv = getattr(lib, prefix + "recv")
    free = getattr(lib, prefix + "msg_free")
    pa, pb = ctypes.c_int(0), ctypes.c_int(0)
    a = bind(b"127.0.0.1", 0, ctypes.byref(pa))
    b = bind(b"127.0.0.1", 0, ctypes.byref(pb))
    try:
        send(a, b"127.0.0.1", pb.value, 1, b"x", 1)  # warm-up / connect
        free(recv(b, 1, 5000))
        t0 = time.perf_counter()
        for _ in range(n):
            send(a, b"127.0.0.1", pb.value, 1, b"x", 1)
            free(recv(b, 1, 5000))
            send(b, b"127.0.0.1", pa.value, 2, b"y", 1)
            free(recv(a, 2, 5000))
        return (time.perf_counter() - t0) / n
    finally:
        getattr(lib, prefix + "shutdown")(a)
        getattr(lib, prefix + "shutdown")(b)
        getattr(lib, prefix + "free")(a)
        getattr(lib, prefix + "free")(b)


def test_shm_beats_epoll_on_loopback_latency():
    """The headline claim of the fast path (the reference's UCX/eRPC
    role): ping-pong round-trips through the shm ring beat the epoll
    TCP transport on loopback."""
    from madsim_tpu.std import native as native_mod

    shm_rtt = _raw_pingpong_rtt(fastpath, "shmep_")
    tcp_rtt = _raw_pingpong_rtt(native_mod, "msep_")
    assert shm_rtt < tcp_rtt, (shm_rtt, tcp_rtt)
