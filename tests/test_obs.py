"""madsim_tpu.obs — fleet metrics, timeline capture, Perfetto export,
hit-count coverage, campaign persistence, and the obs-off identity.

The subsystem's contract is the coverage-tap discipline generalized:
every observability column is DERIVED state — obs-off runs are
bit-identical to pre-obs traces across layouts and the compacted
runner, and obs-on runs change no trace, verdict, or RNG draw. The
timeline's strongest self-check is the refold: the captured stream
re-hashes to the certified trace.
"""

import io
import json

import numpy as np
import pytest

from madsim_tpu import explore, obs
from madsim_tpu.chaos import CrashStorm, FaultPlan, Partition
from madsim_tpu.check import election_safety
from madsim_tpu.engine import (
    HALT_DONE,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    EngineConfig,
    make_init,
    search_seeds,
)
from madsim_tpu.engine.core import (
    MET_CRASH,
    MET_DELIVERED,
    MET_PAUSE,
    MET_RESTART,
    MET_SENT,
)
from madsim_tpu.models import make_pingpong, make_raft
from madsim_tpu.models.raft import OP_ELECT

RAFT_CFG = EngineConfig(pool_size=64, loss_p=0.02)
RAFT_PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=1),
    Partition(targets=(0, 1, 2, 3, 4)),
), name="obs-test")

_ONES = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731


def _elect_inv(h):
    return election_safety(h, elect_op=OP_ELECT)


class TestObsIdentity:
    def test_obs_off_and_on_identical_traces(self):
        """Metrics, timeline and hit-count taps are derived state:
        enabling all three changes no trace and no verdict."""
        wl = make_raft(record=True)
        kw = dict(n_seeds=16, max_steps=600, plan=RAFT_PLAN,
                  history_invariant=_elect_inv)
        r0 = search_seeds(wl, RAFT_CFG, None, **kw)
        r1 = search_seeds(
            wl, RAFT_CFG, None, metrics=True, timeline_cap=256,
            cov_words=16, cov_hitcount=True, **kw,
        )
        assert np.array_equal(r0.traces, r1.traces)
        assert np.array_equal(r0.ok, r1.ok)
        assert r0.met is None and r0.timeline is None
        assert r1.met.shape == (16, len(METRIC_NAMES))
        assert r1.timeline.tl_t.shape[0] == 16

    def test_obs_identical_across_layouts_and_compact(self):
        wl = make_raft(record=True)
        kw = dict(n_seeds=16, max_steps=600, plan=RAFT_PLAN,
                  history_invariant=_elect_inv, metrics=True,
                  timeline_cap=256, cov_words=16, cov_hitcount=True)
        base = search_seeds(wl, RAFT_CFG, None, layout="scatter", **kw)
        dense = search_seeds(wl, RAFT_CFG, None, layout="dense", **kw)
        comp = search_seeds(wl, RAFT_CFG, None, compact=True, **kw)
        for other in (dense, comp):
            assert np.array_equal(base.traces, other.traces)
            assert np.array_equal(base.met, other.met)
            assert np.array_equal(base.cov, other.cov)
            for f in ("tl_count", "tl_drop", "tl_t", "tl_meta", "tl_args"):
                assert np.array_equal(
                    getattr(base.timeline, f), getattr(other.timeline, f)
                ), f

    def test_build_validation(self):
        wl = make_pingpong(rounds=2)
        with pytest.raises(ValueError, match="cov_hitcount"):
            make_init(wl, EngineConfig(), cov_hitcount=True)
        with pytest.raises(ValueError, match="timeline_cap"):
            make_init(wl, EngineConfig(), timeline_cap=-1)


class TestFleetMetrics:
    def test_counters_match_known_workload(self):
        """Pingpong's message economy is exact: every dispatch of a
        message is a delivery, and the per-seed sent count equals the
        engine's own msg_count stat."""
        wl = make_pingpong(rounds=3)
        cfg = EngineConfig()
        r = search_seeds(wl, cfg, _ONES, n_seeds=8, max_steps=200,
                         metrics=True)
        # sent == the engine's msg_count (the same fold condition)
        rr = search_seeds(wl, cfg, _ONES, n_seeds=8, max_steps=200)
        assert rr.ok.all()
        assert (r.met[:, MET_SENT] > 0).all()
        assert (r.met[:, MET_HALT_CODE] == HALT_DONE).all()
        # lossless config, no chaos: every sent message is delivered
        assert np.array_equal(r.met[:, MET_SENT], r.met[:, MET_DELIVERED])

    def test_chaos_counters(self):
        """A one-crash storm plan shows up as exactly one crash per
        seed (the window is drawn before any raft election can halt
        the scenario)."""
        wl = make_raft()
        plan = FaultPlan((
            CrashStorm(targets=(1, 2, 3), n=1, t_min_ns=1_000_000,
                       t_max_ns=50_000_000, down_min_ns=10_000_000,
                       down_max_ns=50_000_000),
        ), name="c1")
        r = search_seeds(
            wl, EngineConfig(pool_size=96), _ONES, n_seeds=16,
            max_steps=800, plan=plan, metrics=True,
        )
        assert (r.met[:, MET_CRASH] == 1).all()
        # the restart fires unless the seed halted before its time
        assert (r.met[:, MET_RESTART] <= 1).all()
        assert r.met[:, MET_RESTART].sum() > 0
        assert (r.met[:, MET_PAUSE] == 0).all()

    def test_halt_code_time_limit(self):
        wl = make_pingpong(rounds=50)
        cfg = EngineConfig(time_limit_ns=20_000_000)
        r = search_seeds(wl, cfg, _ONES, n_seeds=4, max_steps=2000,
                         metrics=True, require_halt=False)
        assert (r.met[:, MET_HALT_CODE] == HALT_TIME_LIMIT).all()
        assert "time-limit" in r.banner()

    def test_fleet_reduce_matches_host_math(self):
        wl = make_raft()
        r = search_seeds(wl, RAFT_CFG, _ONES, n_seeds=32, max_steps=600,
                         metrics=True)
        fm = obs.fleet_reduce(r.met)
        assert fm.n_seeds == 32
        assert np.array_equal(fm.totals, r.met.astype(np.int64).sum(axis=0))
        assert np.array_equal(fm.mins, r.met.min(axis=0))
        assert np.array_equal(fm.maxs, r.met.max(axis=0))
        # histogram rows partition the seeds
        assert (fm.hist.sum(axis=1) == 32).all()
        assert fm.halt_codes.sum() == 32
        assert "fleet metrics over 32 seeds" in fm.format(histograms=True)

    def test_fleet_metrics_device_only_path(self):
        """The metrics-only sweep reduces on device: it returns only
        (M,)-shaped results and matches the search_seeds-reduced
        values for the same seeds."""
        wl = make_raft()
        fm = obs.fleet_metrics(wl, RAFT_CFG, n_seeds=16, max_steps=600)
        r = search_seeds(wl, RAFT_CFG, _ONES, n_seeds=16, max_steps=600,
                         metrics=True)
        ref = obs.fleet_reduce(r.met)
        assert np.array_equal(fm.totals, ref.totals)
        assert np.array_equal(fm.hist, ref.hist)
        assert np.array_equal(fm.halt_codes, ref.halt_codes)

    def test_merge_metrics_sharded_equals_host(self):
        from madsim_tpu.parallel import make_mesh, merge_metrics

        rng = np.random.default_rng(1)
        met = rng.integers(0, 1000, size=(64, 13), dtype=np.int32)
        host = met.astype(np.int64).sum(axis=0)
        assert np.array_equal(merge_metrics(met), host)
        assert np.array_equal(merge_metrics(met, make_mesh()), host)


class TestTimeline:
    def test_refold_recovers_certified_trace(self):
        """The captured stream IS the folded stream: re-hashing the
        decoded timeline reproduces each seed's trace hash — including
        under an injected chaos plan."""
        wl = make_raft(record=True)
        r = search_seeds(
            wl, RAFT_CFG, None, n_seeds=8, max_steps=600,
            plan=RAFT_PLAN, history_invariant=_elect_inv,
            metrics=True, timeline_cap=512,
        )
        assert not r.tl_dropped.any()
        for s in range(8):
            events = obs.decode_timeline(r.timeline, wl, s)
            assert len(events) > 0
            assert obs.refold_timeline(events, wl) == int(r.traces[s])

    def test_overflow_is_loud_not_quarantining(self):
        wl = make_raft()
        r = search_seeds(wl, RAFT_CFG, _ONES, n_seeds=4, max_steps=600,
                         timeline_cap=4)
        assert r.tl_dropped.all()
        assert (r.timeline.tl_count == 4).all()
        assert "timeline ring" in r.banner()
        # forensics never voids evidence: verdicts are unaffected
        assert not r.overflowed.any()

    def test_refold_covers_payload_workloads(self):
        """The ring captures payload words, so the refold certificate
        holds for kvchaos-class models too."""
        from madsim_tpu.models import make_kvchaos

        wl = make_kvchaos(writes=3, record=True, chaos=True, payload=True)
        assert wl.payload_words > 0
        cfg = EngineConfig(pool_size=192)
        r = search_seeds(wl, cfg, _ONES, n_seeds=2, max_steps=4000,
                         timeline_cap=2048, require_halt=False)
        assert not r.tl_dropped.any()
        for s in range(2):
            events = obs.decode_timeline(r.timeline, wl, s)
            assert any(any(w != 0 for w in e.pay) for e in events)
            assert obs.refold_timeline(events, wl) == int(r.traces[s])


class TestPerfetto:
    def _events(self):
        wl = make_raft()
        # kill early so the fault lands before the election halts
        plan = FaultPlan((
            CrashStorm(targets=(1, 2), n=1, t_min_ns=1_000_000,
                       t_max_ns=50_000_000, down_min_ns=10_000_000,
                       down_max_ns=50_000_000),
        ), name="p")
        r = search_seeds(
            wl, EngineConfig(pool_size=96), _ONES, n_seeds=2,
            max_steps=800, plan=plan, timeline_cap=512,
        )
        return wl, obs.decode_timeline(r.timeline, wl, 0)

    def test_valid_trace_event_json_with_matching_count(self):
        wl, events = self._events()
        doc = obs.to_perfetto(events, wl, seed=0)
        # valid JSON end to end
        rt = json.loads(json.dumps(doc))
        assert rt["otherData"]["events"] == len(events)
        disp = [e for e in rt["traceEvents"] if e.get("cat") == "dispatch"]
        assert len(disp) == len(events)
        # every event names required trace-event fields
        for e in rt["traceEvents"]:
            assert "ph" in e and "pid" in e
            if e["ph"] in ("X", "i", "s", "f"):
                assert "ts" in e

    def test_node_tracks_and_chaos_spans(self):
        wl, events = self._events()
        doc = obs.to_perfetto(events, wl)
        names = [
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        ]
        assert any(n.startswith("node 0") for n in names)
        assert "chaos" in names
        spans = [
            e for e in doc["traceEvents"]
            if e.get("cat") == "chaos" and e["ph"] == "X"
        ]
        assert any(s["name"].startswith("killed") for s in spans)

    def test_flow_arrows_pair_up(self):
        wl, events = self._events()
        doc = obs.to_perfetto(events, wl)
        starts = [e for e in doc["traceEvents"]
                  if e.get("cat") == "flow" and e["ph"] == "s"]
        ends = [e for e in doc["traceEvents"]
                if e.get("cat") == "flow" and e["ph"] == "f"]
        assert len(starts) == len(ends) > 0
        assert {e["id"] for e in starts} == {e["id"] for e in ends}

    def test_write_perfetto(self, tmp_path):
        wl, events = self._events()
        p = tmp_path / "trace.json"
        doc = obs.write_perfetto(str(p), events, wl)
        assert json.loads(p.read_text()) == json.loads(json.dumps(doc))


class TestHitcountCoverage:
    def test_traces_unchanged_and_bitmaps_bucketed(self):
        """Hit-counting changes which bits exist, never the run."""
        wl = make_raft(record=True)
        kw = dict(n_seeds=16, max_steps=600, cov_words=16,
                  history_invariant=_elect_inv)
        r0 = search_seeds(wl, RAFT_CFG, None, **kw)
        r1 = search_seeds(wl, RAFT_CFG, None, cov_hitcount=True, **kw)
        assert np.array_equal(r0.traces, r1.traces)
        assert r1.cov.any()
        # bucketed and set-only bitmaps are different coordinate systems
        assert not np.array_equal(r0.cov, r1.cov)

    def test_hitcount_deterministic_across_layouts(self):
        wl = make_raft(record=True)
        kw = dict(n_seeds=8, max_steps=600, cov_words=16,
                  cov_hitcount=True, history_invariant=_elect_inv)
        a = search_seeds(wl, RAFT_CFG, None, layout="dense", **kw)
        b = search_seeds(wl, RAFT_CFG, None, layout="scatter", **kw)
        c = search_seeds(wl, RAFT_CFG, None, compact=True, **kw)
        assert np.array_equal(a.cov, b.cov)
        assert np.array_equal(a.cov, c.cov)

    # tier-1 budget: the bucketing QUALITY claim (recurrence grows
    # coverage) is OBS_r09 cert 4's re-measurement; tier-1 keeps the
    # hit-count identity/determinism rows in this class.
    @pytest.mark.slow
    def test_recurrence_becomes_coverage(self):
        """More rounds of the same behavior grow bucketed coverage
        faster than set-only coverage (which only gains time-phase
        bits) — the AFL refinement's whole point."""
        cfg = EngineConfig()
        cov_n = lambda rounds, hc: explore.popcount(  # noqa: E731
            search_seeds(
                make_pingpong(rounds=rounds), cfg, _ONES, n_seeds=1,
                max_steps=400, cov_words=16, cov_hitcount=hc,
            ).cov
        )
        d_set = cov_n(16, False) - cov_n(4, False)
        d_hc = cov_n(16, True) - cov_n(4, True)
        assert d_hc > d_set
        assert cov_n(16, True) > cov_n(16, False)


class TestCampaignPersistence:
    KW = dict(batch=24, root_seed=11, max_steps=600, cov_words=16)

    def _space(self):
        return FaultPlan((CrashStorm(targets=(1, 2, 3), n=1),), name="t")

    def test_resume_equals_uninterrupted(self, tmp_path):
        wl = make_raft(record=True)
        path = str(tmp_path / "camp.json")
        full = explore.run(wl, RAFT_CFG, self._space(), generations=4,
                           history_invariant=_elect_inv, **self.KW)
        explore.run(wl, RAFT_CFG, self._space(), generations=2,
                    history_invariant=_elect_inv, checkpoint_path=path,
                    **self.KW)
        res = explore.run(wl, RAFT_CFG, self._space(), generations=2,
                          history_invariant=_elect_inv, resume=path,
                          **self.KW)
        fp = lambda r: (  # noqa: E731
            [(e.id, e.seed, e.plan.hash(), e.trace) for e in r.corpus],
            r.cov_map.tolist(),
            [(e.seed, e.trace) for e in r.violations],
            r.curve, r.next_id, r.generations, r.sims,
        )
        assert fp(full) == fp(res)

    def test_resume_validates_campaign_identity(self, tmp_path):
        wl = make_raft(record=True)
        path = str(tmp_path / "camp.json")
        rep = explore.run(wl, RAFT_CFG, self._space(), generations=1,
                          history_invariant=_elect_inv, **self.KW)
        explore.save_campaign(path, rep)
        with pytest.raises(ValueError, match="root seed"):
            explore.run(wl, RAFT_CFG, self._space(), generations=1,
                        history_invariant=_elect_inv, resume=path,
                        **{**self.KW, "root_seed": 12})
        other = FaultPlan((CrashStorm(targets=(1, 2), n=1),), name="x")
        with pytest.raises(ValueError, match="plan-space"):
            explore.run(wl, RAFT_CFG, other, generations=1,
                        history_invariant=_elect_inv, resume=path,
                        **self.KW)

    def test_state_roundtrip_exact(self, tmp_path):
        wl = make_raft(record=True)
        path = str(tmp_path / "camp.json")
        rep = explore.run(wl, RAFT_CFG, self._space(), generations=2,
                          history_invariant=_elect_inv, **self.KW)
        st = explore.save_campaign(path, rep)
        back = explore.load_campaign(path)
        assert np.array_equal(back.cov_map, st.cov_map)
        assert [e.id for e in back.corpus] == [e.id for e in st.corpus]
        for a, b in zip(back.corpus, st.corpus):
            assert (a.seed, a.trace, a.plan.hash()) == (
                b.seed, b.trace, b.plan.hash()
            )
            assert np.array_equal(a.cov, b.cov)


class TestCampaignTelemetry:
    def test_jsonl_records(self):
        wl = make_raft(record=True)
        buf = io.StringIO()
        explore.run(
            wl, RAFT_CFG,
            FaultPlan((CrashStorm(targets=(1, 2, 3), n=1),), name="t"),
            generations=2, batch=16, root_seed=3, max_steps=600,
            cov_words=16, history_invariant=_elect_inv,
            telemetry=obs.JsonlSink(buf),
        )
        recs = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert recs[0]["event"] == "campaign_start"
        assert recs[-1]["event"] == "campaign_end"
        gens = [r for r in recs if r["event"] == "generation"]
        assert len(gens) == 2
        for g in gens:
            for key in ("cov_bits", "corpus_size", "violations",
                        "dispatch_wall_s", "sims"):
                assert key in g


class TestExplain:
    def test_narrative_contains_the_story(self):
        wl = make_raft(record=True)
        plan = FaultPlan((CrashStorm(targets=(1, 2, 3), n=1),), name="t")
        text = obs.explain(
            wl, EngineConfig(pool_size=96), seed=5, plan=plan,
            history_invariant=_elect_inv, max_steps=600,
        )
        assert "injected fault plan" in text
        assert "kill" in text
        assert "history:" in text  # the recorded election event
        assert "verdict: history invariant HOLDS" in text
        assert "repro: seed=5" in text

    def test_narrative_flags_violation(self):
        """The kvchaos lost-write mutant's explain says VIOLATED."""
        from madsim_tpu.check import stale_reads
        from madsim_tpu.models import make_kvchaos

        wl = make_kvchaos(writes=6, record=True, bug=True, chaos=True)
        cfg = EngineConfig(pool_size=192)
        box = {}

        def inv(h):
            box["ok"] = stale_reads(h)
            return box["ok"]

        r = search_seeds(wl, cfg, None, n_seeds=64, max_steps=4000,
                         history_invariant=inv)
        bad = r.failing_seeds
        if not len(bad):
            pytest.skip("mutant not caught in this tiny sweep")
        text = obs.explain(
            wl, cfg, seed=int(bad[0]), history_invariant=inv,
            max_steps=4000,
        )
        assert "history invariant VIOLATED" in text


class TestExplainDiff:
    """obs.explain_diff: localize the first divergent timeline row
    between a clean and a violating sibling."""

    # shared across the class so the telemetry capture cache reuses the
    # compiled run (id(wl) keys the cache)
    WL = make_raft(record=True)
    CFG = EngineConfig(pool_size=96)

    def test_localizes_first_divergence(self):
        wl, cfg = self.WL, self.CFG
        # an early kill perturbs the election prefix, so the plan-driven
        # sibling departs from the bare seeded run mid-stream
        plan = FaultPlan(
            (CrashStorm(
                targets=(0, 1, 2, 3, 4), n=2, t_min_ns=5_000_000,
                t_max_ns=60_000_000, down_min_ns=200_000_000,
                down_max_ns=400_000_000,
            ),),
            name="early",
        )
        text = obs.explain_diff(
            wl, cfg, (5, None), (5, plan),
            history_invariant=_elect_inv, max_steps=600,
            timeline_cap=1024,
        )
        assert "first divergent timeline row" in text
        assert "clean continues:" in text
        assert "violating continues:" in text
        assert "violating plan" in text
        assert "clean outcome:" in text and "violating outcome:" in text
        assert "history invariant" in text
        # the divergence index is a certified statement over the
        # captured stream: the common prefix really is common
        import re

        m = re.search(r"first divergent timeline row: (\d+)", text)
        div = int(m.group(1))
        ev_a = obs.decode_timeline(
            obs.telemetry._capture(wl, cfg, 5, None, 600, 1024, None)[0],
            wl, 0,
        )
        ev_b = obs.decode_timeline(
            obs.telemetry._capture(wl, cfg, 5, plan, 600, 1024, None)[0],
            wl, 0,
        )
        for i in range(div):
            assert obs.telemetry._row_key(ev_a[i]) == obs.telemetry._row_key(
                ev_b[i]
            )
        assert (
            div == min(len(ev_a), len(ev_b))
            or obs.telemetry._row_key(ev_a[div])
            != obs.telemetry._row_key(ev_b[div])
        )

    def test_identical_runs_report_identity(self):
        # same (wl, cfg, caps) as above: the capture cache makes this
        # re-trace nothing
        text = obs.explain_diff(
            self.WL, self.CFG, (7, None), (7, None), max_steps=600,
            timeline_cap=1024,
        )
        assert "timelines IDENTICAL" in text
