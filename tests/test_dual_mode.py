"""Dual-mode services: the SAME service classes that run in simulation
run over real localhost TCP — the reference's cfg-switch drop-in
contract (madsim-etcd-client/src/lib.rs:1-8; madsim-rdkafka vendors the
real API for its std build)."""

import asyncio

import pytest

from madsim_tpu.services import etcd, grpc, kafka


def run(coro):
    return asyncio.run(coro)


class Greeter:
    SERVICE_NAME = "helloworld.Greeter"

    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(3):
            yield {"message": f"reply #{i}"}


def test_greeter_over_real_tcp():
    async def main():
        server_task = asyncio.create_task(
            grpc.Server.builder().add_service(Greeter()).serve("127.0.0.1:55061")
        )
        await asyncio.sleep(0.2)
        try:
            ch = await grpc.connect("127.0.0.1:55061")
            c = grpc.service_client(Greeter, ch)
            r = await asyncio.wait_for(c.say_hello({"name": "world"}), 10)
            assert r["message"] == "Hello world!"
            got = []
            stream = await asyncio.wait_for(c.lots_of_replies({"name": "x"}), 10)
            async for item in stream:
                got.append(item["message"])
            assert got == ["reply #0", "reply #1", "reply #2"]
            await ch.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_kv_over_real_tcp():
    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:55062"))
        await asyncio.sleep(0.2)
        try:
            c = await etcd.Client.connect(["127.0.0.1:55062"])
            r1 = await asyncio.wait_for(c.put("k1", "v1"), 10)
            r2 = await asyncio.wait_for(c.put("k1", "v2"), 10)
            assert r2["header_revision"] == r1["header_revision"] + 1
            g = await asyncio.wait_for(c.get("k1"), 10)
            kv = g["kvs"][0]
            assert kv.value == b"v2" and kv.version == 2
            d = await asyncio.wait_for(
                c.delete("k", etcd.DeleteOptions(prefix=True)), 10
            )
            assert d["deleted"] == 1
            await c.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_txn_and_lease_over_real_tcp():
    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:55063"))
        await asyncio.sleep(0.2)
        try:
            c = await etcd.Client.connect(["127.0.0.1:55063"])
            await asyncio.wait_for(c.put("k", "1"), 10)
            t = (
                etcd.Txn()
                .when([etcd.Compare.value("k", "=", "1")])
                .and_then([etcd.TxnOp.put("k", "2")])
                .or_else([etcd.TxnOp.put("k", "bad")])
            )
            r = await asyncio.wait_for(c.txn(t), 10)
            assert r["succeeded"]
            g = await asyncio.wait_for(c.get("k"), 10)
            assert g["kvs"][0].value == b"2"
            lease = await asyncio.wait_for(c.lease_client().grant(ttl=60), 10)
            await asyncio.wait_for(
                c.put("ephemeral", "x", etcd.PutOptions(lease=lease["id"])), 10
            )
            ttl = await asyncio.wait_for(
                c.lease_client().time_to_live(lease["id"]), 10
            )
            assert ttl["keys"] == [b"ephemeral"]
            await c.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_observe_over_real_tcp():
    """Server-streaming (observe) and its cancellation work over the
    std backend too."""

    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:55065"))
        await asyncio.sleep(0.2)
        try:
            c1 = await etcd.Client.connect(["127.0.0.1:55065"])
            obs = await etcd.Client.connect(["127.0.0.1:55065"])
            lease = await asyncio.wait_for(c1.lease_client().grant(ttl=60), 10)
            stream = await obs.election_client().observe("mayor")
            win = await asyncio.wait_for(
                c1.election_client().campaign("mayor", "alice", lease["id"]), 10
            )
            first = await asyncio.wait_for(stream.message(), 10)
            assert first["kv"].value == b"alice"
            await asyncio.wait_for(c1.election_client().proclaim(win["key"], "alice2"), 10)
            second = await asyncio.wait_for(stream.message(), 10)
            assert second["kv"].value == b"alice2"
            stream.close()
            await c1.close()
            await obs.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_kafka_produce_fetch_over_real_tcp():
    async def main():
        broker = kafka.SimBroker()
        server_task = asyncio.create_task(broker.serve("127.0.0.1:55064"))
        await asyncio.sleep(0.2)
        try:
            cfg = kafka.ClientConfig().set("bootstrap.servers", "127.0.0.1:55064")
            admin = await cfg.create(kafka.AdminClient)
            await asyncio.wait_for(
                admin.create_topics([kafka.NewTopic("t", 1)]), 10
            )
            producer = await cfg.create(kafka.FutureProducer)
            for i in range(5):
                await asyncio.wait_for(
                    producer.send(kafka.BaseRecord.to("t").set_payload(f"m{i}")),
                    10,
                )
            ccfg = (
                kafka.ClientConfig()
                .set("bootstrap.servers", "127.0.0.1:55064")
                .set("auto.offset.reset", "earliest")
            )
            consumer = await ccfg.create(kafka.BaseConsumer)
            tpl = kafka.TopicPartitionList()
            tpl.add_partition("t", 0)
            await consumer.assign(tpl)
            got = []
            idle = 0
            while len(got) < 5 and idle < 50:
                msg = await asyncio.wait_for(consumer.poll(), 10)
                if msg is None:
                    idle += 1
                    await asyncio.sleep(0.05)
                else:
                    got.append(msg.payload)
            assert sorted(got) == [b"m0", b"m1", b"m2", b"m3", b"m4"]
            for cl in (admin, producer, consumer):
                await cl.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())
