"""Dual-mode services: the SAME service classes that run in simulation
run over real localhost TCP — the reference's cfg-switch drop-in
contract (madsim-etcd-client/src/lib.rs:1-8; madsim-rdkafka vendors the
real API for its std build).

Every server binds port 0 and the tests read the kernel-assigned port
from ``server.local_addr`` — no hardcoded ports, safe under parallel
test runs.
"""

import asyncio

import pytest

from madsim_tpu.services import etcd, grpc, kafka


def run(coro):
    return asyncio.run(coro)


async def wait_bound(server, task) -> str:
    """Wait until the server publishes its bound ('ip', port)."""
    for _ in range(100):
        if server.local_addr is not None:
            host, port = server.local_addr
            return f"127.0.0.1:{port}"
        if task.done():
            task.result()  # surface the bind error
        await asyncio.sleep(0.02)
    raise TimeoutError("server never bound")


class Greeter:
    SERVICE_NAME = "helloworld.Greeter"

    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(3):
            yield {"message": f"reply #{i}"}


def test_greeter_over_real_tcp():
    async def main():
        router = grpc.Server.builder().add_service(Greeter())
        server_task = asyncio.create_task(router.serve("127.0.0.1:0"))
        addr = await wait_bound(router, server_task)
        try:
            ch = await grpc.connect(addr)
            c = grpc.service_client(Greeter, ch)
            r = await asyncio.wait_for(c.say_hello({"name": "world"}), 10)
            assert r["message"] == "Hello world!"
            got = []
            stream = await asyncio.wait_for(c.lots_of_replies({"name": "x"}), 10)
            async for item in stream:
                got.append(item["message"])
            assert got == ["reply #0", "reply #1", "reply #2"]
            await ch.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_kv_over_real_tcp():
    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:0"))
        addr = await wait_bound(server, server_task)
        try:
            c = await etcd.Client.connect([addr])
            r1 = await asyncio.wait_for(c.put("k1", "v1"), 10)
            r2 = await asyncio.wait_for(c.put("k1", "v2"), 10)
            assert r2["header_revision"] == r1["header_revision"] + 1
            g = await asyncio.wait_for(c.get("k1"), 10)
            kv = g["kvs"][0]
            assert kv.value == b"v2" and kv.version == 2
            d = await asyncio.wait_for(
                c.delete("k", etcd.DeleteOptions(prefix=True)), 10
            )
            assert d["deleted"] == 1
            await c.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_txn_and_lease_over_real_tcp():
    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:0"))
        addr = await wait_bound(server, server_task)
        try:
            c = await etcd.Client.connect([addr])
            await asyncio.wait_for(c.put("k", "1"), 10)
            t = (
                etcd.Txn()
                .when([etcd.Compare.value("k", "=", "1")])
                .and_then([etcd.TxnOp.put("k", "2")])
                .or_else([etcd.TxnOp.put("k", "bad")])
            )
            r = await asyncio.wait_for(c.txn(t), 10)
            assert r["succeeded"]
            g = await asyncio.wait_for(c.get("k"), 10)
            assert g["kvs"][0].value == b"2"
            lease = await asyncio.wait_for(c.lease_client().grant(ttl=60), 10)
            await asyncio.wait_for(
                c.put("ephemeral", "x", etcd.PutOptions(lease=lease["id"])), 10
            )
            ttl = await asyncio.wait_for(
                c.lease_client().time_to_live(lease["id"]), 10
            )
            assert ttl["keys"] == [b"ephemeral"]
            await c.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_etcd_observe_over_real_tcp():
    """Server-streaming (observe) and its cancellation work over the
    std backend too."""

    async def main():
        server = etcd.SimServer()
        server_task = asyncio.create_task(server.serve("127.0.0.1:0"))
        addr = await wait_bound(server, server_task)
        try:
            c1 = await etcd.Client.connect([addr])
            obs = await etcd.Client.connect([addr])
            lease = await asyncio.wait_for(c1.lease_client().grant(ttl=60), 10)
            stream = await obs.election_client().observe("mayor")
            win = await asyncio.wait_for(
                c1.election_client().campaign("mayor", "alice", lease["id"]), 10
            )
            first = await asyncio.wait_for(stream.message(), 10)
            assert first["kv"].value == b"alice"
            await asyncio.wait_for(
                c1.election_client().proclaim(win["key"], "alice2"), 10
            )
            second = await asyncio.wait_for(stream.message(), 10)
            assert second["kv"].value == b"alice2"
            stream.close()
            await c1.close()
            await obs.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_kafka_produce_fetch_over_real_tcp():
    async def main():
        broker = kafka.SimBroker()
        server_task = asyncio.create_task(broker.serve("127.0.0.1:0"))
        addr = await wait_bound(broker, server_task)
        try:
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            admin = await cfg.create(kafka.AdminClient)
            await asyncio.wait_for(
                admin.create_topics([kafka.NewTopic("t", 1)]), 10
            )
            producer = await cfg.create(kafka.FutureProducer)
            for i in range(5):
                await asyncio.wait_for(
                    producer.send(kafka.BaseRecord.to("t").set_payload(f"m{i}")),
                    10,
                )
            ccfg = (
                kafka.ClientConfig()
                .set("bootstrap.servers", addr)
                .set("auto.offset.reset", "earliest")
            )
            consumer = await ccfg.create(kafka.BaseConsumer)
            tpl = kafka.TopicPartitionList()
            tpl.add_partition("t", 0)
            await consumer.assign(tpl)
            got = []
            idle = 0
            while len(got) < 5 and idle < 50:
                msg = await asyncio.wait_for(consumer.poll(), 10)
                if msg is None:
                    idle += 1
                    await asyncio.sleep(0.05)
                else:
                    got.append(msg.payload)
            assert sorted(got) == [b"m0", b"m1", b"m2", b"m3", b"m4"]
            for cl in (admin, producer, consumer):
                await cl.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_kafka_consumer_group_over_real_tcp():
    """The group protocol (join/sync/heartbeat/rebalance + committed
    offsets) works over the std backend: two members split partitions;
    when one leaves, the survivor inherits everything and resumes from
    the departed member's committed offsets."""

    async def main():
        broker = kafka.SimBroker()
        server_task = asyncio.create_task(broker.serve("127.0.0.1:0"))
        addr = await wait_bound(broker, server_task)
        try:
            cfg = kafka.ClientConfig().set("bootstrap.servers", addr)
            admin = await cfg.create(kafka.AdminClient)
            await admin.create_topics([kafka.NewTopic("jobs", 4)])
            producer = await cfg.create(kafka.FutureProducer)
            for i in range(20):
                await producer.send(
                    kafka.BaseRecord.to("jobs").set_payload(str(i))
                )

            def ccfg():
                return (
                    kafka.ClientConfig()
                    .set("bootstrap.servers", addr)
                    .set("group.id", "workers")
                    .set("auto.offset.reset", "earliest")
                    .set("session.timeout.ms", "30000")
                    .set("heartbeat.interval.ms", "100")
                )

            c1 = await ccfg().create(kafka.BaseConsumer)
            await c1.subscribe(["jobs"])
            c2 = await ccfg().create(kafka.BaseConsumer)
            await c2.subscribe(["jobs"])

            # c1's next poll rejoins at the new generation
            got1 = []
            for _ in range(30):
                m = await asyncio.wait_for(c1.poll(), 10)
                if m is None:
                    await asyncio.sleep(0.05)
                else:
                    got1.append(int(m.payload))
            a1, a2 = c1.assignment(), c2.assignment()
            assert len(a1) == 2 and len(a2) == 2 and not (set(a1) & set(a2))

            await c1.commit()
            await c1.close()  # leave_group -> immediate rebalance

            got2 = []
            idle = 0
            while idle < 30:
                m = await asyncio.wait_for(c2.poll(), 10)
                if m is None:
                    idle += 1
                    await asyncio.sleep(0.05)
                else:
                    idle = 0
                    got2.append(int(m.payload))
            assert set(c2.assignment()) == {("jobs", p) for p in range(4)}
            # everything not consumed (and committed) by c1 reaches c2
            assert set(got1) | set(got2) == set(range(20))
            for cl in (admin, producer, c2):
                await cl.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())


def test_typed_codegen_greeter_over_real_tcp():
    """Generated message types round-trip over the std backend: the
    client sends a generated HelloRequest, the server answers with a
    generated HelloReply, both restored to their classes after real
    TCP + pickling (madsim-tonic-build typed-stub parity)."""
    from madsim_tpu.services.grpc_codegen import compile_proto

    ns = compile_proto("examples/proto/helloworld.proto")

    class TypedGreeter(ns.GreeterServicer):
        async def say_hello(self, request):
            assert isinstance(request.message, ns.HelloRequest)
            return ns.HelloReply(message=f"Hello {request.message.name}!")

        async def lots_of_replies(self, request):
            for i in range(2):
                yield ns.HelloReply(message=f"#{i} {request.message.name}")

    async def main():
        router = grpc.Server.builder().add_service(TypedGreeter())
        server_task = asyncio.create_task(router.serve("127.0.0.1:0"))
        addr = await wait_bound(router, server_task)
        try:
            ch = await grpc.connect(addr)
            c = ns.GreeterClient(ch)
            r = await asyncio.wait_for(c.say_hello(ns.HelloRequest(name="tcp")), 10)
            assert isinstance(r, ns.HelloReply) and r.message == "Hello tcp!"
            stream = await asyncio.wait_for(
                c.lots_of_replies(ns.HelloRequest(name="s")), 10
            )
            got = [m.message async for m in stream]
            assert got == ["#0 s", "#1 s"]
            await ch.close()
        finally:
            server_task.cancel()
        return True

    assert run(main())
