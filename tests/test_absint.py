"""madsim_tpu.lint.absint: the interval walker on hand-built jaxprs,
the overflow/lane provers over the engine, both planted mutants, and
the checked absint pragma allowlist."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from madsim_tpu.engine import EngineConfig, Workload
from madsim_tpu.engine.rng import PURPOSE_LANES, lane, lane_of
from madsim_tpu.lint import (
    absint_matrix,
    absint_model_matrix,
    absint_pragma_inventory,
    analyze_intervals,
    check_ranges,
    plant_lane_collision,
    plant_time32_sentinel_decay,
    stale_absint_pragmas,
)
from madsim_tpu.lint.absint import ABSINT_AXES, AVal
from madsim_tpu.lint.rules import lint_source
from madsim_tpu.models import make_raft

CFG = EngineConfig(pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
H = 60 * 1_000_000_000

# each check_ranges call traces a full step program — share the
# expensive reports across tests (module-scope fixtures)


@pytest.fixture(scope="module")
def rep_int64():
    return check_ranges(
        make_raft(record=True), CFG, entry="step", layout="scatter",
        time32=False, horizon_ns=H, metrics=True, timeline_cap=8,
        cov_words=8,
    )


@pytest.fixture(scope="module")
def rep_t32_indexed():
    return check_ranges(
        make_raft(record=True), CFG, entry="step", layout="scatter",
        time32=True, pool_index=True, horizon_ns=H,
    )


def _iv(lo, hi, tag=None):
    tags = frozenset({tag}) if tag else frozenset()
    return AVal(lo, hi, tags, None, (lo, hi))


class TestIntervalWalker:
    """The walker on hand-built jaxprs — every construct the engine's
    step/run programs route ranges through."""

    def test_add_mul_propagation(self):
        def f(x, y):
            return x + y, x * y, x - jnp.int64(5)

        closed = jax.make_jaxpr(f)(jnp.int64(0), jnp.int64(0))
        w = analyze_intervals(closed, [_iv(0, 10), _iv(2, 3)])
        assert (w.out[0].lo, w.out[0].hi) == (2, 13)
        assert (w.out[1].lo, w.out[1].hi) == (0, 30)
        assert (w.out[2].lo, w.out[2].hi) == (-5, 5)
        assert not w.findings

    def test_overflow_flagged_only_when_tracked_and_signed(self):
        def f(x):
            return x + jnp.int32(1)

        closed = jax.make_jaxpr(f)(jnp.int32(0))
        top = 2**31 - 1
        # tracked time tag + signed overflow -> finding with the site
        w = analyze_intervals(closed, [_iv(0, top, "time:now")])
        assert len(w.findings) == 1
        assert w.findings[0]["rule"] == "absint-overflow"
        assert w.findings[0]["sources"] == ["time:now"]
        assert w.findings[0]["chain"]
        # same range, untagged -> wraps silently (not a tracked value)
        w2 = analyze_intervals(closed, [_iv(0, top)])
        assert not w2.findings
        assert (w2.out[0].lo, w2.out[0].hi) == (-(2**31), 2**31 - 1)

        # unsigned arithmetic is modular by definition: never flagged
        def g(x):
            return x + jnp.uint32(1)

        closedu = jax.make_jaxpr(g)(jnp.uint32(0))
        w3 = analyze_intervals(
            closedu, [_iv(0, 2**32 - 1, "counter:step")]
        )
        assert not w3.findings

    def test_scan_fixpoint_widens_untagged_carry(self):
        # carry grows every iteration: only widening terminates, and
        # the result must cover the divergence (dtype range)
        def f(c, xs):
            def body(carry, x):
                return carry + x, carry

            return lax.scan(body, c, xs)

        closed = jax.make_jaxpr(f)(jnp.int64(0), jnp.arange(3))
        # no contract on the carry: nothing narrows the divergence
        w = analyze_intervals(closed, [AVal(0, 1), AVal(1, 1)])
        assert w.out[0].hi == 2**63 - 1
        assert not w.findings  # untagged: growth is not a finding

    def test_scan_contract_narrowing_keeps_tagged_carry_bounded(self):
        # the assume-guarantee boundary: a carry with a declared
        # contract re-enters each iteration narrowed to it, so bounded
        # increments never diverge and no overflow is reported
        def f(c, xs):
            def body(carry, x):
                return carry + x, carry

            return lax.scan(body, c, xs)

        closed = jax.make_jaxpr(f)(jnp.int64(0), jnp.arange(3))
        w = analyze_intervals(
            closed, [_iv(0, 1000, "time:now"), AVal(0, 5)]
        )
        assert not w.findings
        # one body application past the contract at most
        assert w.out[0].hi <= 1005

    def test_cond_branch_join(self):
        def f(p, x, y):
            return lax.cond(p, lambda: x + jnp.int64(1), lambda: y)

        closed = jax.make_jaxpr(f)(True, jnp.int64(0), jnp.int64(0))
        w = analyze_intervals(
            closed, [AVal(0, 1), _iv(10, 20), _iv(-5, 0)]
        )
        assert (w.out[0].lo, w.out[0].hi) == (-5, 21)

    def test_while_fixpoint_terminates(self):
        def f(x):
            return lax.while_loop(
                lambda c: c[1] < 8, lambda c: (c[0] + c[1], c[1] + 1),
                (x, jnp.int64(0)),
            )

        closed = jax.make_jaxpr(f)(jnp.int64(0))
        w = analyze_intervals(closed, [_iv(0, 4)])
        assert w.out[0].hi == 2**63 - 1  # widened, terminated

    def test_unknown_prim_conservative_top(self):
        def f(x):
            return jnp.cumprod(x)  # no transfer implemented

        closed = jax.make_jaxpr(f)(jnp.arange(4))
        w = analyze_intervals(closed, [_iv(1, 2, "counter:met")])
        assert (w.out[0].lo, w.out[0].hi) == (-(2**63), 2**63 - 1)
        assert "counter:met" in w.out[0].tags  # tags still flow

    def test_onehot_sum_refinement(self):
        # sum(where(m, x, 0)) is the engine's pick idiom: modeled as a
        # pick (hull with 0) under the default trust, as n*x without it
        def f(m, x):
            return jnp.sum(jnp.where(m, x, 0))

        closed = jax.make_jaxpr(f)(np.zeros(8, bool), np.zeros(8, np.int64))
        ivs = [AVal(0, 1), _iv(5, 100, "time:ev_time")]
        w = analyze_intervals(closed, ivs, onehot_sums=True)
        assert (w.out[0].lo, w.out[0].hi) == (0, 100)
        w2 = analyze_intervals(closed, ivs, onehot_sums=False)
        assert w2.out[0].hi == 800

    def test_meta_unpack_stays_bounded(self):
        # the ev_meta byte decode: full uint32 word -> [0, 255] bytes
        def f(meta):
            return ((meta >> jnp.uint32(8)) & jnp.uint32(0xFF)).astype(
                jnp.int32
            ) - 1

        closed = jax.make_jaxpr(f)(jnp.uint32(0))
        w = analyze_intervals(closed, [AVal(0, 2**32 - 1)])
        assert (w.out[0].lo, w.out[0].hi) == (-1, 254)


class TestOverflowProver:
    def test_int64_step_proves_clean(self, rep_int64):
        assert rep_int64.ok, rep_int64.summary()
        assert rep_int64.checked_ops > 20
        assert rep_int64.n_eqns > 500

    def test_time32_indexed_step_proves_clean_via_pragmas(
        self, rep_t32_indexed
    ):
        # the stale-slot rebases are the acknowledged wrap surface:
        # the proof holds exactly because those three sites carry
        # checked per-site pragmas (core.py), not a blanket exclusion
        assert rep_t32_indexed.ok, rep_t32_indexed.summary()
        files = {p[0] for p in rep_t32_indexed.used_pragmas}
        assert files == {"madsim_tpu/engine/core.py"}
        assert len(rep_t32_indexed.used_pragmas) == 3
        assert len(rep_t32_indexed.allowed) >= 3

    def test_run_entry_scan_path(self):
        rep = check_ranges(
            make_raft(record=True), CFG, entry="run", layout="scatter",
            time32=False, horizon_ns=H, n_steps=3,
        )
        assert rep.ok, rep.summary()

    def test_sentinel_decay_mutant_caught_with_chain(self):
        rep = check_ranges(
            make_raft(record=True), CFG, entry="step", layout="scatter",
            time32=True, pool_index=True, horizon_ns=H,
            mutate=plant_time32_sentinel_decay,
        )
        assert not rep.ok
        hits = [
            f for f in rep.findings
            if f["rule"] == "absint-overflow"
            and any(t.endswith("tile_min") for t in f["sources"])
        ]
        assert hits, rep.findings
        f = hits[0]
        # the chain cites the mutant's own (un-pragma'd) site — the
        # SimState vocabulary names the wrapped column
        assert f["chain"]
        assert f["file"] == "madsim_tpu/lint/absint.py"
        assert f["dtype"] == "int32"

    def test_shared_mutant_controls_catch_both(self):
        # the one control recipe the soak gates share (lint_soak cert
        # 5, absint_soak cert 2): both planted mutants judged caught
        from madsim_tpu.lint import run_mutant_controls

        controls = run_mutant_controls()
        assert [n for n, _r, _c in controls] == [
            "time32-sentinel-decay", "lane-collision",
        ]
        assert all(caught for _n, _r, caught in controls)

    def test_mutant_requires_the_indexed_time32_build(self):
        mut = plant_time32_sentinel_decay
        step = lambda st: st  # noqa: E731 — shape probe only
        from madsim_tpu.engine import make_init

        st = make_init(make_raft(), CFG, pool_index=False)(
            np.zeros(1, np.uint64)
        )
        tmpl = jax.tree.map(lambda a: a[0], st)
        with pytest.raises(ValueError, match="pool_index"):
            mut(step)(tmpl)


class TestLaneProver:
    def test_engine_lanes_resolve_and_disjoint(self, rep_int64):
        assert rep_int64.ok
        # raft prefetches its one user purpose, so the whole step is
        # ONE batched cipher site covering the engine + user lanes
        assert len(rep_int64.lane_sites) == 1
        assert {"poll_cost", "latency", "user"} <= set(rep_int64.lanes)

    def test_dup_axis_lights_the_dup_lane(self):
        rep = check_ranges(
            make_raft(record=True), CFG, entry="step", layout="scatter",
            dup_rows=True, horizon_ns=H,
        )
        assert rep.ok, rep.summary()
        assert "dup" in rep.lanes

    def test_lane_collision_mutant_caught(self):
        rep = check_ranges(
            make_raft(record=True), CFG, entry="step", layout="scatter",
            horizon_ns=H, mutate=plant_lane_collision,
        )
        assert not rep.ok
        hits = [f for f in rep.findings if f["rule"] == "absint-lane"]
        assert hits
        assert len(hits[0]["sites"]) == 2  # both colliding sites cited

    def test_registry_is_sorted_and_disjoint(self):
        prev_end = 0
        for ln in PURPOSE_LANES:
            assert ln.base >= prev_end
            assert ln.end <= 1 << 32
            prev_end = ln.end
        assert lane_of(lane("latency").base + 5).name == "latency"
        assert lane_of(7) is None  # unassigned gap below latency

    def test_huge_purpose_rejected_before_uint32_wrap(self):
        # a purpose >= 2^32 wraps back onto a small lane at draw time
        # (Draw.user casts to uint32) — validation must reject the RAW
        # offset, not the wrapped absolute (which would look in-lane)
        from madsim_tpu.engine.rng import validate_user_purposes

        with pytest.raises(ValueError, match="outside the user lane"):
            validate_user_purposes((1 << 32,))
        with pytest.raises(ValueError, match="outside the user lane"):
            validate_user_purposes(((1 << 32) + 5,))
        with pytest.raises(ValueError):
            validate_user_purposes((-1,))

    def test_clamp_hull_respects_variable_lower_bound(self):
        # clamp with a variable LOWER bound can RAISE x: the sound
        # hull must include the bound's upper corner, else a tracked
        # add downstream could be certified clean while wrapping
        def f(lo, x):
            return lax.clamp(lo, x, jnp.int64(2**62))

        closed = jax.make_jaxpr(f)(jnp.int64(0), jnp.int64(0))
        w = analyze_intervals(
            closed, [_iv(0, 2**61, "time:now"), _iv(0, 10)]
        )
        assert w.out[0].hi == 2**61
        assert w.out[0].lo == 0

    def test_draw_purposes_validated_against_registry(self):
        # an out-of-range user lane used to alias the plan block
        # silently; now the build fails naming the aliased lane
        user_width = lane("user").width
        with pytest.raises(ValueError, match="plan"):
            Workload(
                name="bad", n_nodes=1, state_width=1,
                handlers=(lambda ctx: (ctx.state, None),),
                draw_purposes=(user_width,),
            )
        with pytest.raises(ValueError, match="duplicates"):
            Workload(
                name="bad", n_nodes=1, state_width=1,
                handlers=(lambda ctx: (ctx.state, None),),
                draw_purposes=(3, 3),
            )


class TestPragmaHygiene:
    def test_stale_absint_pragma_reported(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text("x = 1  # lint: allow(absint-overflow)\n")
        inv = absint_pragma_inventory(paths=[f], root=tmp_path)
        assert inv == [("mod.py", 1, "absint-overflow")]
        stale = stale_absint_pragmas(set(), paths=[f], root=tmp_path)
        assert len(stale) == 1 and stale[0]["rule"] == "unused-allow"
        # an exercised pragma is not stale
        assert not stale_absint_pragmas(
            {("mod.py", 1, "absint-overflow")}, paths=[f], root=tmp_path
        )

    def test_ast_linter_leaves_absint_pragmas_to_the_prover(self):
        res = lint_source("x = 1  # lint: allow(absint-overflow)\n")
        assert not res.findings  # not an AST-side unused-allow
        # but a stale AST-rule pragma is still a finding
        res2 = lint_source("x = 1  # lint: allow(np-random)\n")
        assert [f.rule for f in res2.findings] == ["unused-allow"]

    def test_repo_absint_pragmas_live_only_in_the_engine(self):
        # pragma creep guard: today's allowlist is exactly the three
        # stale-slot rebase sites in engine/core.py — growing it is a
        # deliberate act this pin makes visible
        inv = absint_pragma_inventory()
        assert {p[0] for p in inv} == {"madsim_tpu/engine/core.py"}
        assert len(inv) == 3


@pytest.mark.slow
class TestFullMatrix:
    """The full layout-matrix sweep — the ~870s tier-1 budget is
    protected by the smoke above; this is the soak-scale gate."""

    def test_full_step_matrix_proves_clean(self):
        reports = absint_matrix()
        bad = [r.summary() for r in reports if not r.ok]
        assert not bad, bad

    def test_run_entry_matrix_proves_clean(self):
        reports = absint_matrix(
            axes={"all": ABSINT_AXES["all"]},
            layouts=(
                ("scatter", False, None), ("scatter", True, None, True),
            ),
            entry="run",
        )
        bad = [r.summary() for r in reports if not r.ok]
        assert not bad, bad

    def test_matrix_names_every_recorded_model(self):
        tags = {m[0] for m in absint_model_matrix()}
        assert {
            "raft/record", "raftlog/durable", "kvchaos/army",
            "paxos/record",
        } <= tags
