"""explore.run_device — the device-resident campaign is a lowering of
the host driver, not a fork.

Every test pins one clause of the contract: bit-identical campaign
outcomes (corpus ids/seeds/plans/traces/new-bit scores, coverage map,
violation set, curves) against ``explore.run`` given the same
arguments, across engine layouts, across checkpoint save/resume in
BOTH directions, and — slow-marked — across a multi-chip mesh. The
telemetry tests make the one-host-sync-per-generation claim checkable
from the artifact rather than from this module's word.
"""

import json

import numpy as np
import pytest

import jax

from madsim_tpu import explore
from madsim_tpu.chaos import FaultPlan, GrayFailure, PauseStorm
from madsim_tpu.engine import EngineConfig
from madsim_tpu.models import make_raft
from madsim_tpu.parallel import make_mesh

NODES = (0, 1, 2, 3, 4)

CFG = EngineConfig(pool_size=64, loss_p=0.02)
PLAN = FaultPlan((
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="device-explore-test")


def _halt_inv(view):
    # jnp-traceable on the device path, numpy-evaluable on the host
    # path — the SAME predicate object drives both drivers
    return view["halted"]


def _biased_inv(view):
    # a deterministic pure-function-of-final-state "bug": seeds whose
    # trace hash lands in the low eighth are violations. Exercises the
    # violation store, (seed, trace) dedup and replay machinery on both
    # paths without needing a planted model mutant.
    return (view["trace"] & 7) != 0


KW = dict(generations=3, batch=24, root_seed=11, max_steps=600,
          cov_words=16, invariant=_halt_inv)

# the uninterrupted host campaign both checkpoint-interop tests splice
# against — computed once (tier-1 wall is a budgeted resource)
_FULL_CACHE: dict = {}


def _full_host_fp():
    if "fp" not in _FULL_CACHE:
        _FULL_CACHE["fp"] = _fingerprint(
            explore.run(make_raft(), CFG, PLAN, **KW)
        )
    return _FULL_CACHE["fp"]


def _fingerprint(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.name, e.plan.hash(),
          e.trace, e.new_bits, e.violating, e.halt_t) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


class TestDeviceParity:
    def test_device_matches_host_and_layouts(self):
        """One host campaign, one device campaign per layout: all three
        produce the same corpus, coverage map and curves — and the
        gen-0 seed-corpus override rides along on every path."""
        seed_lp = PLAN.literalize(3)
        kw = dict(KW, seed_corpus=(seed_lp,))
        host = explore.run(make_raft(), CFG, PLAN, **kw)
        dev = explore.run_device(make_raft(), CFG, PLAN, **kw)
        dense = explore.run_device(
            make_raft(), CFG, PLAN, layout="dense", **kw
        )
        assert _fingerprint(host) == _fingerprint(dev)
        assert _fingerprint(host) == _fingerprint(dense)
        assert dev.host_syncs == kw["generations"]
        assert host.host_syncs == 0  # the notion is device-driver-only
        # the seed-corpus entry keeps its literal name on both paths
        names = {e.plan.name for e in dev.corpus}
        assert seed_lp.name in names

    def test_violations_dedup_and_replay(self):
        """The violation machinery is bit-identical too: same deduped
        (seed, trace) set, and a device-found violation replays to its
        recorded trace through the ordinary host replay path."""
        kw = dict(KW, invariant=_biased_inv, generations=2)
        host = explore.run(make_raft(), CFG, PLAN, **kw)
        dev = explore.run_device(make_raft(), CFG, PLAN, **kw)
        assert _fingerprint(host) == _fingerprint(dev)
        assert dev.violations, "the biased invariant must flag seeds"
        e = dev.violations[-1]
        r = explore.replay_entry(
            make_raft(), CFG, e, invariant=_biased_inv, max_steps=800,
        )
        assert int(r.traces[0]) == e.trace
        assert int(r.failing_seeds[0]) == e.seed

    def test_checkpoint_interop_host_to_device(self, tmp_path):
        """A host-driver checkpoint resumes on the device driver (and
        the spliced campaign equals the uninterrupted host one)."""
        p = str(tmp_path / "camp.npz")
        explore.run(
            make_raft(), CFG, PLAN,
            **dict(KW, generations=2, checkpoint_path=p),
        )
        resumed = explore.run_device(
            make_raft(), CFG, PLAN,
            **dict(KW, generations=1), resume=p,
        )
        assert _full_host_fp() == _fingerprint(resumed)
        # the wall split / sync count cover only the RESUMED run — the
        # banner must pair them against 1 generation, not all 3
        assert resumed.generations == 3
        assert resumed.host_syncs == 1 and resumed.wall_gens == 1
        assert "1 summary syncs / 1 generations" in resumed.banner()

    def test_checkpoint_interop_device_to_host(self, tmp_path):
        p = str(tmp_path / "camp.npz")
        explore.run_device(
            make_raft(), CFG, PLAN,
            **dict(KW, generations=2, checkpoint_path=p),
        )
        resumed = explore.run(
            make_raft(), CFG, PLAN,
            **dict(KW, generations=1), resume=p,
        )
        assert _full_host_fp() == _fingerprint(resumed)

    def test_telemetry_one_sync_per_generation(self, tmp_path):
        """The artifact proves the claim: every generation record has
        ``host_syncs: 1`` and a dispatch/sync wall split; campaign_end
        totals them."""
        records = []
        rep = explore.run_device(
            make_raft(), CFG, PLAN, telemetry=records.append,
            **dict(KW, generations=2, batch=8),
        )
        gens = [r for r in records if r["event"] == "generation"]
        assert len(gens) == 2
        for r in gens:
            assert r["host_syncs"] == 1
            assert "dispatch_wall_s" in r and "sync_wall_s" in r
        end = records[-1]
        assert end["event"] == "campaign_end"
        assert end["host_syncs"] == 2
        assert rep.host_syncs == 2
        # every record is JSONL-serializable (the artifact format)
        for r in records:
            json.dumps(r)
        assert "host sync" in rep.banner()

    def test_host_driver_banner_reports_wall_split(self):
        rep = explore.run(
            make_raft(), CFG, PLAN, **dict(KW, generations=1, batch=8)
        )
        assert rep.wall_dispatch_s > 0.0
        assert "batched dispatch" in rep.banner()

    def test_requires_traceable_invariant(self):
        with pytest.raises(ValueError, match="traceable"):
            explore.run_device(
                make_raft(), CFG, PLAN,
                **{**KW, "invariant": None},
            )

    def test_viol_store_overflow_raises(self):
        # everything violates and the store cannot hold the batch: the
        # dedup set would silently break, so the campaign must refuse
        with pytest.raises(RuntimeError, match="viol_cap"):
            explore.run_device(
                make_raft(), CFG, PLAN, viol_cap=2,
                **dict(KW, generations=1, batch=8,
                       invariant=lambda v: v["halted"] & False),
            )


@pytest.mark.slow
@pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU platform"
)
class TestDeviceMesh:
    def test_mesh_campaign_identical(self):
        """Sharding the generation across the 8-device mesh changes no
        bit of the campaign (corpus, coverage, violations), and the
        cross-shard metric fold reports through telemetry."""
        records = []
        host = explore.run(make_raft(), CFG, PLAN, **KW)
        dev = explore.run_device(
            make_raft(), CFG, PLAN, mesh=make_mesh(), metrics=True,
            telemetry=records.append, **KW,
        )
        assert _fingerprint(host) == _fingerprint(dev)
        gens = [r for r in records if r["event"] == "generation"]
        assert all(r["host_syncs"] == 1 for r in gens)
        assert all(len(r["met_total"]) > 0 for r in gens)

    def test_mesh_batch_must_split(self):
        with pytest.raises(ValueError, match="split over"):
            explore.run_device(
                make_raft(), CFG, PLAN, mesh=make_mesh(),
                **dict(KW, batch=12),
            )
