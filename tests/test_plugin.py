"""Custom device-simulator plugin (C6, reference plugin.rs): a
user-defined Simulator gets per-runtime construction with the seeded
rng/time/config + supervisor handle, node-lifecycle callbacks
(create_node on build, reset_node on kill — the power-fail analog),
instance lookup via Handle.simulator()/plugin.simulator(), and full
determinism because its randomness rides the runtime's GlobalRng."""

import madsim_tpu as ms
from madsim_tpu.runtime.plugin import Simulator, node, simulator


class GpsSim(Simulator):
    """A toy device: per-node GPS readings with seeded jitter, wiped on
    node reset like any device state."""

    def __init__(self, rng, time, config, handle):
        super().__init__(rng, time, config, handle)
        self.fixes: dict[int, list] = {}
        self.created: list[int] = []
        self.resets: list[int] = []

    def create_node(self, node_id: int) -> None:
        self.created.append(node_id)
        self.fixes[node_id] = []

    def reset_node(self, node_id: int) -> None:
        self.resets.append(node_id)
        self.fixes[node_id] = []     # device buffer cleared by the crash

    def read_fix(self) -> tuple:
        nid = node()
        fix = (self.time.now_ns(), self.rng.randrange(0, 360))
        self.fixes[nid].append(fix)
        return fix


def run(seed):
    log = []

    async def main():
        h = ms.Handle.current()
        gps = h.simulator(GpsSim)
        assert simulator(GpsSim) is gps      # module-level lookup agrees
        n1 = h.create_node().name("rover-1").build()
        n2 = h.create_node().name("rover-2").build()
        assert n1.id in gps.created and n2.id in gps.created

        async def roam():
            for _ in range(3):
                await ms.sleep(0.5)
                log.append((node(), gps.read_fix()))

        a, b = n1.spawn(roam()), n2.spawn(roam())
        await a
        await b
        # kill wipes the device state through reset_node
        pre = len(gps.fixes[n1.id])
        assert pre == 3
        h.kill(n1)
        h.restart(n1)
        await ms.sleep(0.1)
        assert n1.id in gps.resets
        assert gps.fixes[n1.id] == []
        return tuple(log)

    rt = ms.Runtime(seed=seed)
    rt.add_simulator(GpsSim)
    out = rt.block_on(main())
    return out


def test_custom_simulator_lifecycle_and_determinism():
    a = run(5)
    assert a == run(5), "custom-simulator runs must be bit-identical"
    assert a != run(9), "different seeds explore different readings"
    # readings advanced on virtual time and used the seeded rng
    assert all(t > 0 and 0 <= bearing < 360 for _n, (t, bearing) in a)


def test_simulator_registered_after_nodes_backfills():
    """add_simulator after nodes exist back-fills create_node
    (runtime.add_simulator's existing-node loop, mod.rs:68-79)."""
    rt = ms.Runtime(seed=1)

    async def make_node():
        ms.Handle.current().create_node().name("early").build()

    rt.block_on(make_node())
    sim = rt.add_simulator(GpsSim)
    assert len(sim.created) >= 2  # main node + early
