"""Simulation-aware tracing (SURVEY §5 tracing parity): records carry
virtual time, node, task and seed; same-seed runs log identically."""

import logging

import madsim_tpu as ms


def _capture(seed):
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(ms.SimFormatter())
    sink.addFilter(ms.SimContextFilter())
    log = logging.getLogger("test_trace")
    log.setLevel(logging.INFO)
    log.addHandler(sink)
    try:
        async def main():
            h = ms.Handle.current()
            node = h.create_node().name("srv").ip("10.0.0.1").build()

            async def work():
                log.info("starting")
                with ms.span("phase1"):
                    await ms.sleep(0.5)
                    log.info("inside span")
                log.info("after span")

            await node.spawn(work())

        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(30)
        rt.block_on(main())
    finally:
        log.removeHandler(sink)
    return records


def test_records_carry_sim_context():
    recs = _capture(7)
    assert len(recs) == 3
    assert "node=1(srv)" in recs[0] and "seed=7" in recs[0]
    assert "phase1" in recs[1]
    assert "phase1" not in recs[2]
    # virtual timestamps: the span body slept 0.5 simulated seconds
    t0 = float(recs[0].split("[")[1].split("s ")[0])
    t1 = float(recs[1].split("[")[1].split("s ")[0])
    assert t1 - t0 >= 0.5


def test_same_seed_logs_identically():
    assert _capture(3) == _capture(3)
    assert _capture(3) != _capture(4)


def _capture_interleaved(seed):
    """Two concurrent tasks with nested spans, interleaved across await
    points — the span() docstring's per-task claim under task switches."""
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(ms.SimFormatter())
    sink.addFilter(ms.SimContextFilter())
    log = logging.getLogger("test_trace_nest")
    log.setLevel(logging.INFO)
    log.addHandler(sink)
    try:
        async def main():
            h = ms.Handle.current()
            node = h.create_node().name("srv").build()

            async def worker(tag, delay):
                with ms.span(f"outer-{tag}"):
                    log.info("enter %s", tag)
                    await ms.sleep(delay)  # the other task runs here
                    with ms.span(f"inner-{tag}"):
                        log.info("deep %s", tag)
                        await ms.sleep(delay)
                        log.info("deep2 %s", tag)
                    log.info("shallow %s", tag)
                log.info("exit %s", tag)

            t1 = node.spawn(worker("a", 0.3))
            t2 = node.spawn(worker("b", 0.2))
            await t1
            await t2

        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(30)
        rt.block_on(main())
    finally:
        log.removeHandler(sink)
    return records


def test_span_nesting_survives_task_switches():
    """Span stacks are per task: interleaved awaits never leak one
    task's spans into the other's records, and nesting pops in order."""
    recs = _capture_interleaved(5)
    for r in recs:
        for tag, other in (("a", "b"), ("b", "a")):
            if f"enter {tag}" in r or f"shallow {tag}" in r:
                assert f"outer-{tag}" in r and f"inner-{tag}" not in r
                assert f"-{other}" not in r  # no cross-task leak
            if f"deep {tag}" in r or f"deep2 {tag}" in r:
                assert f"outer-{tag}:inner-{tag}" in r
                assert f"-{other}" not in r
            if f"exit {tag}" in r:
                assert "outer-" not in r and "inner-" not in r


def test_interleaved_same_seed_logs_byte_identical():
    """The docstring's determinism claim under real concurrency: two
    same-seed runs of interleaving span-carrying tasks produce
    byte-identical logs; a different seed does not."""
    a, b = _capture_interleaved(9), _capture_interleaved(9)
    assert len(a) == 10  # 5 records per worker
    assert a == b
    assert _capture_interleaved(10) != a  # seeded timestamps differ


def test_no_context_outside_sim():
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(ms.SimFormatter())
    sink.addFilter(ms.SimContextFilter())
    log = logging.getLogger("test_trace_outside")
    log.setLevel(logging.INFO)
    log.addHandler(sink)
    try:
        log.info("plain")
    finally:
        log.removeHandler(sink)
    assert records == ["I plain: test_trace_outside"] or "plain" in records[0]
