"""Simulation-aware tracing (SURVEY §5 tracing parity): records carry
virtual time, node, task and seed; same-seed runs log identically."""

import logging

import madsim_tpu as ms


def _capture(seed):
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(ms.SimFormatter())
    sink.addFilter(ms.SimContextFilter())
    log = logging.getLogger("test_trace")
    log.setLevel(logging.INFO)
    log.addHandler(sink)
    try:
        async def main():
            h = ms.Handle.current()
            node = h.create_node().name("srv").ip("10.0.0.1").build()

            async def work():
                log.info("starting")
                with ms.span("phase1"):
                    await ms.sleep(0.5)
                    log.info("inside span")
                log.info("after span")

            await node.spawn(work())

        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(30)
        rt.block_on(main())
    finally:
        log.removeHandler(sink)
    return records


def test_records_carry_sim_context():
    recs = _capture(7)
    assert len(recs) == 3
    assert "node=1(srv)" in recs[0] and "seed=7" in recs[0]
    assert "phase1" in recs[1]
    assert "phase1" not in recs[2]
    # virtual timestamps: the span body slept 0.5 simulated seconds
    t0 = float(recs[0].split("[")[1].split("s ")[0])
    t1 = float(recs[1].split("[")[1].split("s ")[0])
    assert t1 - t0 >= 0.5


def test_same_seed_logs_identically():
    assert _capture(3) == _capture(3)
    assert _capture(3) != _capture(4)


def test_no_context_outside_sim():
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(ms.SimFormatter())
    sink.addFilter(ms.SimContextFilter())
    log = logging.getLogger("test_trace_outside")
    log.setLevel(logging.INFO)
    log.addHandler(sink)
    try:
        log.info("plain")
    finally:
        log.removeHandler(sink)
    assert records == ["I plain: test_trace_outside"] or "plain" in records[0]
