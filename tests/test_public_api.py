"""Doc-rot guard: every surface MIGRATING.md promises must exist.

The migration guide is the contract for a user switching from the
reference; this pins each named symbol so the doc cannot silently
drift from the package.
"""

import madsim_tpu as ms


def test_top_level_surface():
    for name in [
        "test", "main", "Runtime", "Handle", "Builder", "Config",
        "NodeBuilder", "NodeHandle", "JoinHandle", "spawn", "spawn_local",
        "sleep", "sleep_until", "timeout", "interval", "now", "now_ns",
        "Instant", "SystemTime", "thread_rng", "random", "select",
        "join_all", "Endpoint", "TcpListener", "TcpStream", "UdpSocket",
        "NetSim", "FsSim", "fs", "net", "sync",
        "available_parallelism", "spawn_blocking", "yield_now",
    ]:
        assert hasattr(ms, name), f"MIGRATING.md promises ms.{name}"


def test_handle_and_builder_surface():
    for name in ["kill", "restart", "pause", "resume", "create_node",
                 "current", "get_node"]:
        assert hasattr(ms.Handle, name)
    for name in ["name", "ip", "init", "restart_on_panic", "build"]:
        assert hasattr(ms.NodeBuilder, name)


def test_net_surface():
    from madsim_tpu.net import addr, aio_streams, rpc, service  # noqa: F401

    for name in ["bind", "connect", "connect1", "accept1", "send_to",
                 "recv_from", "recv", "send", "peer_addr", "call"]:
        assert hasattr(ms.Endpoint, name)
    for name in ["clog_node_in", "clog_node_out", "unclog_node_in",
                 "unclog_node_out", "connect", "disconnect", "connect2",
                 "disconnect2", "update_config", "hook_rpc_req",
                 "hook_rpc_rsp"]:
        assert hasattr(ms.NetSim, name)
    assert hasattr(ms.TcpStream, "set_nodelay")
    assert hasattr(addr, "lookup_host")
    for name in [
        "SimTransport", "SimDatagramTransport", "SimServer",
        "create_connection", "create_server", "create_datagram_endpoint",
    ]:
        assert hasattr(aio_streams, name)


def test_services_surface():
    from madsim_tpu.services import etcd, grpc, grpc_codegen, kafka

    assert hasattr(grpc, "Server") and hasattr(grpc, "connect")
    assert hasattr(grpc, "service_client")
    assert any(
        hasattr(grpc_codegen, n)
        for n in ("compile_proto", "codegen", "generate", "compile")
    ), f"no codegen entry point in {dir(grpc_codegen)}"
    assert any(hasattr(etcd, n) for n in ("EtcdClient", "Client")), dir(etcd)
    assert kafka is not None


def test_compat_and_std_surface():
    from madsim_tpu import std
    from madsim_tpu.compat import asyncio as casyncio

    for name in ["sleep", "wait_for", "gather", "Queue", "Lock", "Event"]:
        assert hasattr(casyncio, name)
    from madsim_tpu.std import fastpath, fs, net, time  # noqa: F401

    assert hasattr(fastpath, "pick_endpoint")
    assert std is not None


def test_engine_surface():
    from madsim_tpu import engine, models, parallel

    for name in [
        "EngineConfig", "Workload", "make_init", "make_run",
        "make_run_while", "make_run_compacted", "check_layouts",
        "search_seeds", "threefry2x32",
    ]:
        assert hasattr(engine, name), name
    from madsim_tpu.engine import measure, vmem  # noqa: F401

    assert hasattr(measure, "measure_throughput")
    assert hasattr(measure, "measure_latency")
    # engine re-exports the replay API at package level (the name
    # `engine.replay` is the function, shadowing the module)
    for name in ["replay", "format_timeline", "refold"]:
        assert hasattr(engine, name), name
    assert hasattr(vmem, "make_run_vmem")
    for name in [
        "make_raft", "make_raftlog", "make_paxos", "make_twophase",
        "make_kvchaos", "make_broadcast", "make_microbench",
        "make_pingpong", "BENCH_SPECS",
    ]:
        assert hasattr(models, name), name
    for name in ["make_mesh", "shard_state", "shard_over_seeds",
                 "shard_run_compacted"]:
        assert hasattr(parallel, name), name
