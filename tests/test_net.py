"""Network simulator semantics, mirroring the reference's endpoint/net
tests (SURVEY.md §4: sim/net/endpoint.rs:355-576, sim/net/tcp/mod.rs:
98-208) — tag matching, partitions and recovery, node reset EOF, RPC."""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, NetSim


def run(seed, coro_fn, config=None, time_limit=60.0):
    rt = ms.Runtime(seed=seed, config=config)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


def two_nodes(h):
    a = h.create_node().name("a").ip("10.0.0.1").build()
    b = h.create_node().name("b").ip("10.0.0.2").build()
    return a, b


def test_endpoint_send_recv_across_nodes():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        got = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:500")
            payload, src = await ep.recv_from(tag=7)
            got.set_result((payload, src))

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ep.send_to("10.0.0.2:500", 7, {"hello": "world"})

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        payload, src = await got
        assert payload == {"hello": "world"}
        assert src[0] == "10.0.0.1"
        return True

    assert run(1, main)


def test_tag_matching_order_independent():
    """Receivers get messages by tag regardless of arrival order
    (endpoint.rs tag-matching tests)."""

    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        done = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:500")
            # wait for tag 2 first even though tag 1 arrives first
            p2, _ = await ep.recv_from(tag=2)
            p1, _ = await ep.recv_from(tag=1)
            done.set_result((p1, p2))

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            await ep.send_to("10.0.0.2:500", 1, "one")
            await ms.sleep(0.5)
            await ep.send_to("10.0.0.2:500", 2, "two")

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await done == ("one", "two")
        return True

    assert run(2, main)


def test_connection_ordered_delivery():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        out = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:600")
            _tx, rx, peer = await ep.accept1()
            got = [await rx.recv() for _ in range(50)]
            out.set_result((got, peer))

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            tx, _rx = await ep.connect1("10.0.0.2:600")
            for i in range(50):
                await tx.send(i)

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        got, peer = await out
        assert got == list(range(50))  # reliable AND ordered
        assert peer[0] == "10.0.0.1"
        return True

    assert run(3, main)


def test_connection_refused():
    async def main():
        h = ms.Handle.current()
        a, _b = two_nodes(h)
        result = ms.SimFuture()

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            try:
                await ep.connect1("10.0.0.2:9999")  # nothing bound there
            except ConnectionRefusedError:
                result.set_result("refused")

        a.spawn(client())
        assert await result == "refused"
        return True

    assert run(4, main)


def test_partition_stalls_connection_and_recovers():
    """clog_link blocks the stream; unclog resumes it in order
    (reference tcp/mod.rs:98-174 partition-and-recovery phases)."""

    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        received = []
        ready = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:700")
            ready.set_result(None)
            _tx, rx, _ = await ep.accept1()
            while True:
                m = await rx.recv()
                if m is None:
                    return
                received.append((m, round(ms.now_ns() / 1e9, 1)))

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            tx, _ = await ep.connect1("10.0.0.2:700")
            await tx.send("before")
            await ms.sleep(1.0)
            # partition happens at t~1; these stall
            await tx.send("during-1")
            await tx.send("during-2")

        b.spawn(server())
        await ready
        a.spawn(client())
        await ms.sleep(1.0)
        net.clog_link(a, b)
        await ms.sleep(10.0)
        n_during = len(received)
        net.unclog_link(a, b)
        await ms.sleep(15.0)
        assert [m for m, _ in received] == ["before", "during-1", "during-2"]
        assert n_during == 1  # only "before" got through while clogged
        return True

    assert run(5, main)


def test_packet_loss_drops_datagrams():
    cfg = ms.Config()
    cfg.net.packet_loss_rate = 1.0

    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        got = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:800")
            payload, _ = await ep.recv_from(tag=1)
            got.set_result(payload)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            for _ in range(20):
                await ep.send_to("10.0.0.2:800", 1, "x")

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        with pytest.raises(ms.Elapsed):
            await ms.timeout(30.0, _await_future(got))
        return True

    assert run(6, main, config=cfg, time_limit=120.0)


async def _await_future(fut):
    return await fut


def test_kill_server_gives_eof_and_send_error():
    """Node reset closes connections: peer recv -> EOF, send -> error
    (reference tcp/mod.rs:176-208)."""

    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        status = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:900")
            _tx, rx, _ = await ep.accept1()
            await rx.recv()  # keep the conn alive

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            tx, rx = await ep.connect1("10.0.0.2:900")
            await tx.send("hi")
            eof = await rx.recv()  # blocks until server dies -> EOF
            assert eof is None
            try:
                await tx.send("again")
                status.set_result("send-succeeded")
            except ConnectionResetError:
                status.set_result("send-failed-after-reset")

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        await ms.sleep(2.0)
        h.kill(b)
        assert await status == "send-failed-after-reset"
        return True

    assert run(7, main)


class Echo:
    def __init__(self, text):
        self.text = text


class Fail:
    pass


def test_rpc_echo_and_error_propagation():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        srv_ready = ms.SimFuture()
        result = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:1000")

            async def on_echo(req):
                return f"echo: {req.text}"

            async def on_fail(_req):
                raise ValueError("handler exploded")

            ep.add_rpc_handler(Echo, on_echo)
            ep.add_rpc_handler(Fail, on_fail)
            srv_ready.set_result(None)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            r = await ep.call("10.0.0.2:1000", Echo("hi"))
            try:
                await ep.call("10.0.0.2:1000", Fail())
                result.set_result((r, "no-error"))
            except ValueError as e:
                result.set_result((r, str(e)))

        b.spawn(server())
        await srv_ready
        a.spawn(client())
        assert await result == ("echo: hi", "handler exploded")
        return True

    assert run(8, main)


def test_rpc_timeout_on_clogged_node():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        result = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:1100")

            async def on_echo(req):
                return req.text

            ep.add_rpc_handler(Echo, on_echo)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            try:
                await ep.call("10.0.0.2:1100", Echo("x"), timeout=5.0)
                result.set_result("ok")
            except ms.Elapsed:
                result.set_result("timeout")

        b.spawn(server())
        await ms.sleep(0.1)
        net.clog_node(b)
        a.spawn(client())
        assert await result == "timeout"
        return True

    assert run(9, main)


def test_send_hook_drops_matching_messages():
    """The RPC-drop chaos hook (reference net/mod.rs:223-262)."""

    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        got = []

        async def server():
            ep = await Endpoint.bind("0.0.0.0:1200")
            while True:
                payload, _ = await ep.recv_from(tag=1)
                got.append(payload)

        def drop_evens(_src, _dst, msg):
            if msg[0] == "dgram" and isinstance(msg[2], int) and msg[2] % 2 == 0:
                return False
            return True

        hook_id = net.add_send_hook(drop_evens)
        b.spawn(server())
        await ms.sleep(0.1)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            for i in range(6):
                await ep.send_to("10.0.0.2:1200", 1, i)

        a.spawn(client())
        await ms.sleep(5.0)
        net.remove_send_hook(hook_id)
        assert sorted(got) == [1, 3, 5]
        return True

    assert run(10, main)


def test_stat_counts_messages():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("0.0.0.0:1300")
            while True:
                await ep.recv_from(tag=1)

        b.spawn(server())
        await ms.sleep(0.1)

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            for _ in range(5):
                await ep.send_to("10.0.0.2:1300", 1, "m")

        a.spawn(client())
        await ms.sleep(5.0)
        assert net.stat.msg_count == 5
        return True

    assert run(11, main)


def test_ephemeral_port_allocation():
    async def main():
        h = ms.Handle.current()
        a, _ = two_nodes(h)
        ports = ms.SimFuture()

        async def t():
            e1 = await Endpoint.bind("0.0.0.0:0")
            e2 = await Endpoint.bind("0.0.0.0:0")
            ports.set_result((e1.local_addr[1], e2.local_addr[1]))

        a.spawn(t())
        p1, p2 = await ports
        assert p1 != p2
        assert p1 >= 0x8000 and p2 >= 0x8000
        return True

    assert run(12, main)


def test_localhost_isolation():
    """127.0.0.1 resolves to the sender's own node — two nodes' loopback
    endpoints do not see each other (endpoint.rs localhost tests)."""

    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        got_a, got_b = [], []

        async def local_server(sink):
            ep = await Endpoint.bind("127.0.0.1:1400")
            while True:
                p, _ = await ep.recv_from(tag=1)
                sink.append(p)

        async def local_client(tag_val):
            ep = await Endpoint.bind("127.0.0.1:0")
            await ep.send_to("127.0.0.1:1400", 1, tag_val)

        a.spawn(local_server(got_a))
        b.spawn(local_server(got_b))
        await ms.sleep(0.1)
        a.spawn(local_client("from-a"))
        b.spawn(local_client("from-b"))
        await ms.sleep(5.0)
        assert got_a == ["from-a"]
        assert got_b == ["from-b"]
        return True

    assert run(13, main)


def test_tcp_udp_endpoint_port_namespaces_coexist():
    """Sockets are keyed by (addr, protocol): UDP, TCP and Endpoint can
    share a port number (reference network.rs:24-70)."""
    from madsim_tpu.net import TcpListener, UdpSocket

    async def main():
        h = ms.Handle.current()
        a, _ = two_nodes(h)
        out = ms.SimFuture()

        async def t():
            await UdpSocket.bind("0.0.0.0:53")
            await TcpListener.bind("0.0.0.0:53")
            await Endpoint.bind("0.0.0.0:53")
            out.set_result("all-bound")

        a.spawn(t())
        assert await out == "all-bound"
        return True

    assert run(20, main)


def test_send_without_ip_fails_loudly():
    """A node without an IP cannot address remote peers; the error must be
    immediate, not a silently-misrouted reply."""

    async def main():
        h = ms.Handle.current()
        _a, _b = two_nodes(h)
        # main node (node 0) has no IP
        ep = await Endpoint.bind("0.0.0.0:0")
        try:
            await ep.send_to("10.0.0.2:500", 1, "x")
            return "sent"
        except OSError as e:
            return "no-ip-error" if "no IP" in str(e) else f"other: {e}"

    assert run(21, main) == "no-ip-error"


def test_rpc_timeout_cleans_mailbox():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        done = ms.SimFuture()

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            net.clog_node(b)
            for _ in range(10):
                try:
                    await ep.call("10.0.0.2:1", Echo("x"), timeout=1.0)
                except ms.Elapsed:
                    pass
            done.set_result(len(ep._mailbox.waiters) + len(ep._mailbox.msgs))

        a.spawn(client())
        assert await done == 0
        return True

    assert run(22, main, time_limit=120.0)


def test_pipe_registry_does_not_grow_across_connections():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)

        async def server():
            ep = await Endpoint.bind("0.0.0.0:600")
            while True:
                _tx, rx, _ = await ep.accept1()

                async def drain(rx=rx):
                    while await rx.recv() is not None:
                        pass

                ms.spawn(drain())

        async def client():
            ep = await Endpoint.bind("0.0.0.0:0")
            for _ in range(30):
                tx, _rx = await ep.connect1("10.0.0.2:600")
                await tx.send("hi")
                tx.close()  # full close releases both directions

        b.spawn(server())
        await ms.sleep(0.1)
        await a.spawn(client())
        await ms.sleep(30.0)
        n_live = sum(len(s) for s in net._pipes_by_node.values())
        # each closed connection must deregister its pipes; only the last
        # connection's reverse-direction pipes may linger
        assert n_live <= 8, f"pipe registry leaked: {n_live} live entries"
        return True

    assert run(23, main, time_limit=240.0)


# ---- directional clogs, aliases, live config, typed RPC hooks ----------
# (mod.rs:131-136, 152-213, 223-264 parity)

class _PingReq:
    def __init__(self, n):
        self.n = n


def _kv_service(results):
    async def server():
        ep = await Endpoint.bind("0.0.0.0:700")
        ep.add_rpc_handler(_PingReq, _handler(results))
        await ms.sleep(1000)
    return server


def _handler(results):
    async def handle(req):
        results.append(req.n)
        return req.n * 10
    return handle


def test_directional_node_clog():
    """clog_node_in blocks deliveries TO the node while its own sends
    still flow; clog_node_out is the mirror (mod.rs:183-192)."""
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        got_b, got_a = [], []

        async def rx(node_list, port):
            ep = await Endpoint.bind(f"0.0.0.0:{port}")
            while True:
                payload, _ = await ep.recv_from(tag=1)
                node_list.append(payload)

        b.spawn(rx(got_b, 600))
        a.spawn(rx(got_a, 600))
        await ms.sleep(0.1)

        async def send(frm, to_ip, val):
            ep = await Endpoint.bind("0.0.0.0:0")
            await ep.send_to(f"{to_ip}:600", 1, val)

        # in-clog on b: a->b blocked, b->a flows
        net.clog_node_in(b)
        a.spawn(send(a, "10.0.0.2", "a2b-clogged"))
        b.spawn(send(b, "10.0.0.1", "b2a-ok"))
        await ms.sleep(1.0)
        assert got_b == [] and got_a == ["b2a-ok"]
        net.unclog_node_in(b)

        # out-clog on b: b->a blocked, a->b flows
        net.clog_node_out(b)
        a.spawn(send(a, "10.0.0.2", "a2b-ok"))
        b.spawn(send(b, "10.0.0.1", "b2a-clogged"))
        await ms.sleep(1.0)
        assert got_b == ["a2b-ok"] and got_a == ["b2a-ok"]
        net.unclog_node_out(b)
        return True

    assert run(21, main)


def test_connect_disconnect_aliases():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        received = []

        async def rx():
            ep = await Endpoint.bind("0.0.0.0:610")
            while True:
                p, _ = await ep.recv_from(tag=2)
                received.append(p)

        b.spawn(rx())
        await ms.sleep(0.1)

        async def send(val):
            ep = await Endpoint.bind("0.0.0.0:0")
            await ep.send_to("10.0.0.2:610", 2, val)

        net.disconnect(b)           # = clog_node
        a.spawn(send("while-down"))
        await ms.sleep(0.5)
        assert received == []
        net.connect(b)              # = unclog_node
        net.disconnect2(a, b)       # = clog_link both ways
        a.spawn(send("link-down"))
        await ms.sleep(0.5)
        assert received == []
        net.connect2(a, b)
        a.spawn(send("up")); await ms.sleep(0.5)
        assert received == ["up"]
        return True

    assert run(22, main)


def test_update_config_live():
    """update_config changes apply to subsequent sends (mod.rs:131)."""
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        received = []

        async def rx():
            ep = await Endpoint.bind("0.0.0.0:620")
            while True:
                p, _ = await ep.recv_from(tag=3)
                received.append(p)

        b.spawn(rx())
        await ms.sleep(0.1)

        async def send(val):
            ep = await Endpoint.bind("0.0.0.0:0")
            await ep.send_to("10.0.0.2:620", 3, val)

        net.update_config(lambda c: setattr(c, "packet_loss_rate", 1.0))
        for i in range(10):
            a.spawn(send(i))
        await ms.sleep(1.0)
        assert received == []
        net.update_config(lambda c: setattr(c, "packet_loss_rate", 0.0))
        a.spawn(send("after"))
        await ms.sleep(0.5)
        assert received == ["after"]
        return True

    assert run(23, main)


def test_hook_rpc_req_and_rsp():
    """Typed hooks: req hook on the SENDER drops matching requests; rsp
    hook on the CALLER drops the typed response after the handler ran
    (mod.rs:223-264)."""
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        handled = []
        b.spawn(_kv_service(handled)())
        await ms.sleep(0.1)

        async def call(n, timeout=2.0):
            ep = await Endpoint.bind("0.0.0.0:0")
            try:
                return await ep.call(
                    "10.0.0.2:700", _PingReq(n), timeout=timeout
                )
            except ms.Elapsed:
                return "elapsed"

        # baseline
        r = await a.spawn(call(1))
        assert r == 10 and handled == [1]

        # req hook on sender a: drop odd requests
        net.hook_rpc_req(a, _PingReq, lambda req: req.n % 2 == 0)
        assert await a.spawn(call(2)) == 20
        assert await a.spawn(call(3, timeout=0.5)) == "elapsed"
        assert handled == [1, 2]          # 3 never reached the server
        net.hook_rpc_req(a, _PingReq, None)

        # rsp hook on caller a: handler runs, response dropped
        net.hook_rpc_rsp(a, int, lambda rsp: False)
        assert await a.spawn(call(4, timeout=0.5)) == "elapsed"
        assert handled == [1, 2, 4]       # server DID handle it
        net.hook_rpc_rsp(a, int, None)
        assert await a.spawn(call(5)) == 50
        return True

    assert run(24, main)


def test_endpoint_connect_send_recv():
    """Endpoint.connect pins a default peer; send/recv omit the address
    (endpoint.rs:39-45, 96-113)."""
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        done = ms.SimFuture()

        async def server():
            ep = await Endpoint.bind("0.0.0.0:650")
            payload, src = await ep.recv_from(tag=9)
            await ep.send_to(src, 9, payload * 2)

        async def client():
            ep = await Endpoint.connect("10.0.0.2:650")
            assert ep.peer_addr == ("10.0.0.2", 650)
            await ep.send(9, 21)
            done.set_result(await ep.recv(9))

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await done == 42
        # a bound (unconnected) endpoint has no peer
        ep = await Endpoint.bind("0.0.0.0:0")
        try:
            ep.peer_addr
        except OSError:
            return True
        raise AssertionError("peer_addr on unconnected endpoint must raise")

    assert run(25, main)
