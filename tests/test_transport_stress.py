"""Stress the native transports: concurrency, backpressure, interop,
and teardown races — the failure modes loopback demos don't exercise.

Covers all three C ABI transports (epoll, io_uring, shm) plus the
asyncio endpoint through the same scenarios where each is eligible.
"""

import asyncio
import shutil

import pytest

from madsim_tpu.std import fastpath, native as native_mod, uring as uring_mod
from madsim_tpu.std import net as std_net

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)

TRANSPORTS = [
    pytest.param(native_mod, id="epoll"),
    pytest.param(
        uring_mod,
        id="uring",
        marks=pytest.mark.skipif(
            not uring_mod.available(), reason="io_uring unavailable"
        ),
    ),
    pytest.param(fastpath, id="shm"),
]


def ep_class(mod):
    for name in ("NativeEndpoint", "UringEndpoint", "ShmEndpoint"):
        if hasattr(mod, name):
            return getattr(mod, name)
    raise AssertionError(f"no endpoint class in {mod}")


def run(coro):
    return asyncio.run(coro)


@pytest.mark.parametrize("mod", TRANSPORTS)
def test_concurrent_senders_no_interleaving(mod):
    """8 tasks hammer one receiver concurrently; every message arrives
    intact (framing never interleaves mid-message)."""

    async def main():
        a = await ep_class(mod).bind("127.0.0.1:0")
        b = await ep_class(mod).bind("127.0.0.1:0")
        try:
            per_task, n_tasks = 40, 8

            async def sender(task_id):
                for i in range(per_task):
                    await a.send_to(
                        b.local_addr, 5, (task_id, i, b"x" * (100 + task_id))
                    )

            send_all = asyncio.gather(*[sender(t) for t in range(n_tasks)])
            got = []
            for _ in range(per_task * n_tasks):
                (tid, i, blob), _src = await b.recv_from(5, timeout=30)
                assert blob == b"x" * (100 + tid), "payload corrupted"
                got.append((tid, i))
            await send_all
            # per-sender ordering holds (one connection per peer pair)
            for t in range(n_tasks):
                seq = [i for tid, i in got if tid == t]
                assert seq == sorted(seq), f"sender {t} reordered"
        finally:
            a.close()
            b.close()
        return True

    assert run(main())


@pytest.mark.parametrize("mod", TRANSPORTS)
def test_many_tags_concurrent_receivers(mod):
    """Concurrent blocking receives on distinct tags all complete."""

    async def main():
        a = await ep_class(mod).bind("127.0.0.1:0")
        b = await ep_class(mod).bind("127.0.0.1:0")
        try:
            # two waves of 4: the endpoint's recv pool has 4 workers, so
            # 4 is the maximum number of receives that can genuinely
            # block in the native layer at once — 8 at a time would
            # quietly test mailbox buffering instead
            for wave in (list(range(1, 5)), list(range(5, 9))):

                async def receiver(tag):
                    payload, _ = await b.recv_from(tag, timeout=30)
                    return payload

                recvs = [asyncio.create_task(receiver(t)) for t in wave]
                await asyncio.sleep(0.05)
                for t in reversed(wave):  # deliver in reverse tag order
                    await a.send_to(b.local_addr, t, f"tag-{t}")
                results = await asyncio.gather(*recvs)
                assert results == [f"tag-{t}" for t in wave]
        finally:
            a.close()
            b.close()
        return True

    assert run(main())


@pytest.mark.parametrize("mod", TRANSPORTS)
def test_close_wakes_blocked_receiver(mod):
    """close() while a recv is blocked: the receiver errors out instead
    of hanging (the two-phase shutdown contract)."""

    async def main():
        a = await ep_class(mod).bind("127.0.0.1:0")

        async def blocked():
            # strictly ConnectionError: close() sets _closed before the
            # native shutdown, so a woken receiver reports closure — a
            # TimeoutError here would mean the transport dropped a
            # blocked receive early, which must FAIL this test
            with pytest.raises(ConnectionError):
                await a.recv_from(1, timeout=20)

        task = asyncio.create_task(blocked())
        await asyncio.sleep(0.1)
        # close from the event loop while the pool thread blocks in recv
        await asyncio.get_event_loop().run_in_executor(None, a.close)
        await asyncio.wait_for(task, timeout=10)
        return True

    assert run(main())


@pytest.mark.parametrize("mod", TRANSPORTS)
def test_burst_of_large_payloads(mod):
    """A pipelined burst of 1 MiB payloads survives backpressure."""

    async def main():
        a = await ep_class(mod).bind("127.0.0.1:0")
        b = await ep_class(mod).bind("127.0.0.1:0")
        try:
            blob = bytes(range(256)) * 4096  # 1 MiB
            n = 12

            async def pump():
                for i in range(n):
                    await a.send_to(b.local_addr, 9, (i, blob))

            send = asyncio.create_task(pump())
            for i in range(n):
                (j, got), _ = await b.recv_from(9, timeout=60)
                assert j == i and got == blob
            await send
        finally:
            a.close()
            b.close()
        return True

    assert run(main())


def test_three_way_interop_mesh():
    """epoll, io_uring and asyncio endpoints all talk to each other on
    one wire format (shm is its own medium and excluded)."""
    if not uring_mod.available():
        pytest.skip("io_uring unavailable")

    async def main():
        e = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        u = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        p = await std_net.Endpoint.bind("127.0.0.1:0")
        eps = {"epoll": e, "uring": u, "asyncio": p}
        try:
            tag = 11
            for src_name, src in eps.items():
                for dst_name, dst in eps.items():
                    if src is dst:
                        continue
                    await src.send_to(
                        dst.local_addr, tag, f"{src_name}->{dst_name}"
                    )
            for dst_name, dst in eps.items():
                expected = {
                    f"{s}->{dst_name}" for s in eps if s != dst_name
                }
                got = set()
                for _ in range(len(expected)):
                    if dst is p:
                        payload, _ = await asyncio.wait_for(
                            dst.recv_from(tag), 15
                        )
                    else:
                        payload, _ = await dst.recv_from(tag, timeout=15)
                    got.add(payload)
                assert got == expected, f"{dst_name} got {got}"
        finally:
            e.close()
            u.close()
            await p.close()
        return True

    assert run(main())
