"""Multi-chip correctness: sharded == unsharded, bit-identical.

The seed axis is the scaling axis (SURVEY.md §2.6): sharding it over a
mesh must not change a single bit of any seed's simulation. These tests
run the same seed batch unsharded and sharded 1/2/8 ways over the
virtual 8-device CPU platform (tests/conftest.py) and assert the full
final state — trace hashes, clocks, node state — is identical. This is
the multi-chip claim the driver's dryrun (shape + sharding only) does
not cover.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madsim_tpu.engine import EngineConfig, make_init, make_run, make_run_while
from madsim_tpu.models import make_kvchaos, make_pingpong, make_raft
from madsim_tpu.parallel import (
    make_mesh,
    seed_sharding,
    shard_over_seeds,
    shard_state,
)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU platform"
)


def run_unsharded(wl, cfg, seeds, n_steps):
    init = make_init(wl, cfg)
    run = jax.jit(make_run(wl, cfg, n_steps))
    return jax.block_until_ready(run(init(seeds)))


def run_sharded(wl, cfg, seeds, n_steps, devices):
    mesh = make_mesh(devices)
    init = make_init(wl, cfg)
    state = shard_state(init(seeds), mesh)
    run = shard_over_seeds(make_run(wl, cfg, n_steps), mesh)
    return jax.block_until_ready(run(state))


def assert_states_equal(a, b):
    for name in (
        "trace", "now", "step", "halted", "halt_time", "overflow",
        "msg_count", "node_state", "ev_time", "ev_valid", "ev_meta",
        "alive", "epoch", "clog",
    ):
        av = np.asarray(getattr(a, name))
        bv = np.asarray(getattr(b, name))
        assert np.array_equal(av, bv), f"field {name} diverged"


# the 8-device case is the strongest form (the 1/2-device meshes are
# degenerate/weaker variants of the same claim); they ride the full tier
@pytest.mark.parametrize(
    "n_devices",
    [pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow), 8],
)
def test_raft_sharded_equals_unsharded(n_devices):
    wl = make_raft()
    cfg = EngineConfig(pool_size=64, loss_p=0.05)
    seeds = np.arange(32, dtype=np.uint64)
    ref = run_unsharded(wl, cfg, seeds, 200)
    out = run_sharded(wl, cfg, seeds, 200, jax.devices()[:n_devices])
    assert_states_equal(ref, out)


@pytest.mark.slow
def test_kvchaos_payload_sharded_equals_unsharded():
    # payload arena words must survive the sharded path too
    wl = make_kvchaos(writes=3, payload=True)
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    seeds = np.arange(16, dtype=np.uint64)
    ref = run_unsharded(wl, cfg, seeds, 250)
    out = run_sharded(wl, cfg, seeds, 250, jax.devices())
    assert_states_equal(ref, out)
    assert np.array_equal(np.asarray(ref.ev_pay), np.asarray(out.ev_pay))


def test_run_while_sharded_equals_unsharded():
    # the bench path: early-exit loop with the all-halted reduction as
    # the only cross-shard collective
    wl = make_pingpong(rounds=4)
    cfg = EngineConfig(pool_size=32)
    seeds = np.arange(16, dtype=np.uint64)
    init = make_init(wl, cfg)
    ref = jax.block_until_ready(jax.jit(make_run_while(wl, cfg, 300))(init(seeds)))
    mesh = make_mesh(jax.devices())
    state = shard_state(init(seeds), mesh)
    out = jax.block_until_ready(
        shard_over_seeds(make_run_while(wl, cfg, 300), mesh)(state)
    )
    assert_states_equal(ref, out)
    assert bool(np.all(np.asarray(out.halted)))


def test_shard_over_seeds_round_trip():
    # shard_state places every leaf with seeds split across the mesh;
    # values survive the round trip and the output keeps the sharding
    wl = make_pingpong(rounds=2)
    cfg = EngineConfig(pool_size=32)
    mesh = make_mesh(jax.devices())
    init = make_init(wl, cfg)
    state = init(np.arange(16, dtype=np.uint64))
    host = jax.device_get(state)
    placed = shard_state(state, mesh)
    assert placed.ev_time.sharding.is_equivalent_to(
        seed_sharding(mesh), placed.ev_time.ndim
    )
    back = jax.device_get(placed)
    for name in ("seed", "ev_time", "ev_valid", "node_state"):
        assert np.array_equal(
            np.asarray(getattr(host, name)), np.asarray(getattr(back, name))
        )
    out = shard_over_seeds(make_run(wl, cfg, 50), mesh)(placed)
    assert out.trace.sharding.mesh.shape == mesh.shape


def test_nocheck_kwarg_selection():
    """The replication-check-off kwarg is picked from the resolved
    shard_map's OWN signature — both spellings are live (the pinned
    jax still resolves the pre-graduation fallback, where the kwarg
    is ``check_rep``; post-rename jax calls it ``check_vma``), so the
    selection logic is regression-tested against both instead of
    collapsing the fallback."""
    from madsim_tpu.parallel import _SM_NOCHECK, _nocheck_kwargs, _shard_map

    def old_style(f, *, mesh, in_specs, out_specs, check_rep=True):
        pass

    def new_style(f, *, mesh, in_specs, out_specs, check_vma=True):
        pass

    assert _nocheck_kwargs(old_style) == {"check_rep": False}
    assert _nocheck_kwargs(new_style) == {"check_vma": False}
    # an un-introspectable callable falls back to the current spelling
    assert _nocheck_kwargs(type) == {"check_vma": False}
    # and the module-level pick matches this jax's real shard_map
    assert _SM_NOCHECK == _nocheck_kwargs(_shard_map)


def test_shard_map_nocheck_smoke():
    # the one repo spelling of the pattern: mapped body with a
    # mesh-constant/shard-varying mix the replication checker would
    # reject, value-equal to the unsharded computation
    from jax.sharding import PartitionSpec as P

    from madsim_tpu.parallel import shard_map_nocheck

    mesh = make_mesh(jax.devices())
    ax = mesh.axis_names
    x = np.arange(16, dtype=np.float32)

    def body(v):
        return v * 2.0 + 1.0

    out = jax.jit(
        shard_map_nocheck(body, mesh, in_specs=P(ax), out_specs=P(ax))
    )(x)
    assert np.array_equal(np.asarray(out), body(x))


def test_make_mesh_shapes():
    mesh = make_mesh(jax.devices())
    assert mesh.axis_names == ("host", "chip")
    assert int(np.prod(list(mesh.shape.values()))) == jax.device_count()
    mesh2 = make_mesh(jax.devices(), hosts=2)
    assert mesh2.shape["host"] == 2
    assert mesh2.shape["chip"] == jax.device_count() // 2


def assert_compacted_equal(ref, out):
    """Per-seed equality on every banked result field except 'step'
    (documented divergence, engine/compact.py)."""
    from madsim_tpu.engine.compact import RESULT_FIELDS

    for f in RESULT_FIELDS:
        if f == "step":
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
            err_msg=f,
        )


# ~38 s cold: 2 x 5-phase compaction programs. The sharded+compacted
# combination is also proven (at mesh scale, vs the unsharded banked
# path) by __graft_entry__.dryrun_multichip on every driver run, and
# the default tier keeps sharded-lockstep (raft_sharded[8]) and
# unsharded-compaction (test_compact raft) separately — so both
# families of this test ride the full tier
@pytest.mark.slow
@pytest.mark.parametrize("name", ["raft", "kvchaos"])
def test_shard_run_compacted_equals_unsharded(name):
    # per-device local compaction: phase boundaries fall at different
    # steps than the global runner's, but rows are independent, so
    # per-seed results must be bit-identical to both the unsharded
    # compactor and the lockstep loop
    from madsim_tpu.engine import make_run_compacted
    from madsim_tpu.models import BENCH_SPECS
    from madsim_tpu.parallel import shard_run_compacted

    factory, kw, _, _ = BENCH_SPECS[name]
    wl, cfg = factory(), EngineConfig(**kw)
    seeds = np.arange(128, dtype=np.uint64)
    init = make_init(wl, cfg)
    # compacted == lockstep is already asserted per family by
    # tests/test_compact.py; compiling a third (lockstep) 2000-step
    # program here cost ~20 s cold for no extra information — the claim
    # under test is sharded == unsharded on the compacted path.
    # min_size=8 keeps a compaction boundary inside every 16-seed shard
    # (16→8) while trimming the phase count (and compile) vs min_size=4
    solo = make_run_compacted(wl, cfg, 2000, shrink=2, min_size=8)(init(seeds))
    mesh = make_mesh(jax.devices())
    sharded = shard_run_compacted(
        wl, cfg, 2000, mesh, shrink=2, min_size=8
    )(shard_state(init(seeds), mesh))
    assert_compacted_equal(solo, sharded)


def test_shard_run_compacted_rejects_uneven_split():
    from madsim_tpu.models import BENCH_SPECS
    from madsim_tpu.parallel import shard_run_compacted

    factory, kw, _, _ = BENCH_SPECS["raft"]
    wl, cfg = factory(), EngineConfig(**kw)
    mesh = make_mesh(jax.devices())
    run = shard_run_compacted(wl, cfg, 100, mesh, min_size=4)
    state = make_init(wl, cfg)(np.arange(12, dtype=np.uint64))
    with pytest.raises(ValueError, match="do not split"):
        run(state)


@pytest.mark.slow
def test_shard_run_compacted_at_step_cap():
    # a cap where SOME seeds have halted and some are live: shards hit
    # different compaction points (banked rows diverge per shard) and
    # the live rows must freeze identically to the lockstep loop
    from madsim_tpu.models import BENCH_SPECS
    from madsim_tpu.parallel import shard_run_compacted

    factory, kw, _, _ = BENCH_SPECS["raft"]
    wl, cfg = factory(), EngineConfig(**kw)
    seeds = np.arange(64, dtype=np.uint64)
    init = make_init(wl, cfg)
    cap = 18  # raft seeds halt from ~step 12; the tail runs past 25
    ref = jax.block_until_ready(
        jax.jit(make_run_while(wl, cfg, cap))(init(seeds))
    )
    halted = np.asarray(ref.halted)
    assert halted.any(), "cap must land after the first halts"
    assert not halted.all(), "cap must hit while rows are still live"
    mesh = make_mesh(jax.devices())
    out = shard_run_compacted(wl, cfg, cap, mesh, shrink=2, min_size=2)(
        shard_state(init(seeds), mesh)
    )
    assert_compacted_equal(ref, out)
