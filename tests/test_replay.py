"""engine/replay.py — the failing-seed timeline debugger.

The timeline's credibility rests on one property: the logged events are
EXACTLY the tuples the certified trace hash folds. refold(events) must
therefore equal both the oracle's trace and the batched engine's trace
for the same (seed, config, steps) — proving the human-readable story
and the bit-identical evidence describe the same execution.
"""

import shutil

import numpy as np
import pytest

from madsim_tpu.engine import (
    EngineConfig,
    format_timeline,
    make_init,
    make_run,
    refold,
    replay,
)
from madsim_tpu.models import make_raftlog, make_twophase

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)


def test_replay_refolds_to_engine_trace():
    wl = make_raftlog(n_writes=3)
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    seeds = np.arange(4, dtype=np.uint64)
    out = make_run(wl, cfg, 400)(make_init(wl, cfg)(seeds))
    traces = np.asarray(out.trace)
    for s in range(4):
        events, res = replay(wl, cfg, s, 400, n_writes=3)
        assert res.trace == int(traces[s]), f"oracle vs engine trace, seed {s}"
        assert refold(events, wl) == int(traces[s]), f"refold, seed {s}"
        assert events, "a run must dispatch events"
        times = [e.time_ns for e in events]
        assert times == sorted(times), "timeline is time-ordered"


def test_replay_auto_grows_past_cap():
    wl = make_twophase(txns=4)
    cfg = EngineConfig(pool_size=64, loss_p=0.03)
    events_small, res_small = replay(wl, cfg, 7, 500, cap=8, txns=4)
    events_big, res_big = replay(wl, cfg, 7, 500, cap=65536, txns=4)
    assert res_small.trace == res_big.trace
    assert [e.time_ns for e in events_small] == [e.time_ns for e in events_big]
    assert len(events_small) > 8  # the tiny cap really was outgrown


def test_timeline_renders_named_kinds():
    wl = make_raftlog(n_writes=3)
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    events, res = replay(wl, cfg, 1, 400, n_writes=3)
    text = format_timeline(events, res, wl)
    assert "init(" in text  # handler 0 renders by name
    assert "reqvote(" in text or "timeout(" in text
    assert "halted=" in text
    # engine chaos kinds render by their engine names. The kill fires
    # 200-500ms in and most schedules halt first — scan seeds for one
    # whose schedule reaches the chaos (seed 9 and 11 do at n_writes=4).
    wl4 = make_raftlog(n_writes=4)
    for s in range(12):
        ev_s, _res = replay(wl4, cfg, s, 1000, n_writes=4)
        t = format_timeline(ev_s, wl=wl4)
        if "KILL(" in t or "RESTART(" in t:
            break
    else:
        raise AssertionError("no seed in 0..11 dispatched its chaos kill")
