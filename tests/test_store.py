"""Storage fault injection (madsim_tpu disk chaos).

Five layers under test: the engine's two-phase sync discipline
(synced-data survival, unsynced loss, sync-lie windows, torn-write
kills — and the identity contracts: discipline-off verbatim semantics
and always-synced ≡ verbatim bit-identical across layouts/compact),
the chaos ``DiskFault`` spec + fault-window validation (the
CrashStorm-after-halt satellite), the C++-oracle guard for
extended-kind plans, ``FsSim`` power-failure semantics with the
FsSim↔engine convergence check, and the ``recovery_safety`` detector
plus the raftlog storage certificates (soak-scale pieces marked slow —
``tools/store_soak.py`` is the evidence artifact).
"""

import warnings

import numpy as np
import pytest

import jax

import madsim_tpu as ms
from madsim_tpu import fs
from madsim_tpu.chaos import (
    CrashStorm,
    DiskFault,
    FaultEvent,
    FaultPlan,
    FlappingPartition,
    LiteralPlan,
    Nemesis,
)
from madsim_tpu.check import BatchHistory, election_safety, recovery_safety
from madsim_tpu.engine import (
    EngineConfig,
    Workload,
    make_init,
    make_run_while,
    search_seeds,
    user_kind,
)
from madsim_tpu.engine.core import (
    KIND_KILL,
    KIND_RESTART,
    KIND_SYNC_LOSS,
    KIND_SYNC_OK,
    KIND_TORN_OFF,
    KIND_TORN_ON,
    MET_SYNC,
    MET_SYNC_LOST,
    MET_TORN,
)
from madsim_tpu.check.history import OK_OK
from madsim_tpu.models import make_raftlog
from madsim_tpu.models.raftlog import (
    OP_COMMIT,
    OP_ELECT,
    OP_RECOVER,
    OP_SYNCED,
)

SEEDS = np.arange(64, dtype=np.uint64)
CFG = EngineConfig(pool_size=16)

WRITE_VALS = (11, 22, 33)


def make_probe(sync_call: bool, durable_sync: bool = True) -> Workload:
    """One node, durable cols (1,2,3): handler 1 writes (11,22,33) in a
    single multi-column dispatch at ~10 ms and optionally fsyncs —
    the minimal surface every discipline rule is visible on."""

    def on_init(ctx):
        eb = ctx.emits()
        eb.after(10_000_000, user_kind(1), 0, when=(ctx.now == 0))
        return ctx.state, eb.build()

    def on_write(ctx):
        new = ctx.state
        for j, v in enumerate(WRITE_VALS):
            new = new.at[1 + j].set(v)
        eb = ctx.emits()
        if sync_call:
            eb.sync()
        return new, eb.build()

    return Workload(
        name=f"store-probe-{int(sync_call)}-{int(durable_sync)}",
        n_nodes=1,
        state_width=4,
        handlers=(on_init, on_write),
        max_emits=2,
        delay_bound_ns=300_000_000,
        durable_cols=(1, 2, 3),
        durable_sync=durable_sync,
    )


KILL = LiteralPlan(events=(FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),))


def run_probe(wl, plan, layout=None, metrics=False, seeds=SEEDS):
    init = make_init(wl, CFG, plan_slots=plan.slots, metrics=metrics)
    run = jax.jit(make_run_while(wl, CFG, 60, layout=layout, metrics=metrics))
    return jax.block_until_ready(
        run(init(seeds, plan.compile_batch(seeds)))
    )


def durable_rows(out):
    return np.asarray(out.node_state)[:, 0, 1:]


# ------------------------------------------------- engine sync discipline
class TestSyncDiscipline:
    def test_synced_write_survives_kill(self):
        out = run_probe(make_probe(sync_call=True), KILL, metrics=True)
        assert (durable_rows(out) == WRITE_VALS).all()
        met = np.asarray(out.met)
        assert (met[:, MET_SYNC] == 1).all()
        assert (met[:, MET_SYNC_LOST] == 0).all()

    def test_unsynced_write_lost_on_kill(self):
        out = run_probe(make_probe(sync_call=False), KILL)
        assert (durable_rows(out) == 0).all(), (
            "an unsynced durable write must not survive a kill"
        )
        # the synced disk image is what the node would recover with
        assert (np.asarray(out.disk)[:, 0, 1:] == 0).all()

    def test_sync_loss_window_makes_sync_lie(self):
        lie = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SYNC_LOSS, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        out = run_probe(make_probe(sync_call=True), lie, metrics=True)
        assert (durable_rows(out) == 0).all(), "a lying sync must commit nothing"
        met = np.asarray(out.met)
        assert (met[:, MET_SYNC_LOST] == 1).all()
        assert (met[:, MET_SYNC] == 0).all()
        # a closed window commits again: SYNC_OK before the write
        heal = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SYNC_LOSS, a0=0),
            FaultEvent(t=5_000_000, kind=KIND_SYNC_OK, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        out2 = run_probe(make_probe(sync_call=True), heal)
        assert (durable_rows(out2) == WRITE_VALS).all()

    def test_torn_kill_keeps_prefix_of_last_write(self):
        torn = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        out = run_probe(make_probe(sync_call=False), torn, metrics=True)
        rows = durable_rows(out)
        allowed = {(0, 0, 0)} | {
            WRITE_VALS[: k + 1] + (0,) * (2 - k) for k in range(3)
        }
        got = {tuple(int(x) for x in r) for r in rows}
        assert got <= allowed, f"non-prefix survivors: {got - allowed}"
        # the threefry prefix draw varies over 64 seeds: the tear is a
        # distribution, not a constant
        assert len(got) >= 2
        assert (np.asarray(out.met)[:, MET_TORN] == 1).all()
        # a closed torn window is a clean loss again
        off = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=5_000_000, kind=KIND_TORN_OFF, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        assert (durable_rows(run_probe(make_probe(False), off)) == 0).all()

    def test_torn_never_tears_synced_state(self):
        """A tear only loses *uncommitted* bytes: with the write synced
        in its own dispatch, an armed torn kill changes nothing."""
        torn = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        out = run_probe(make_probe(sync_call=True), torn)
        assert (durable_rows(out) == WRITE_VALS).all()

    def test_discipline_off_keeps_verbatim_semantics(self):
        out = run_probe(make_probe(sync_call=False, durable_sync=False), KILL)
        assert (durable_rows(out) == WRITE_VALS).all()
        # discipline off = zero-size columns (the cov_words rule)
        assert np.asarray(out.disk).shape[1] == 0
        assert np.asarray(out.sync_loss).shape[1] == 0

    def test_always_synced_equals_verbatim_bit_identical(self):
        """The oracle-compatibility contract: sync-every-write under the
        discipline is trajectory-identical to verbatim-durable, across
        layouts — disk-faults-off runs pin to current traces."""
        restart = LiteralPlan(events=(
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
            FaultEvent(t=120_000_000, kind=KIND_RESTART, a0=0),
        ))
        ref = run_probe(
            make_probe(sync_call=False, durable_sync=False), restart,
            layout="scatter",
        )
        for layout in ("scatter", "dense"):
            got = run_probe(make_probe(sync_call=True), restart, layout=layout)
            assert np.array_equal(np.asarray(got.trace), np.asarray(ref.trace))
            assert np.array_equal(
                np.asarray(got.node_state), np.asarray(ref.node_state)
            )

    def test_sync_flag_ignored_without_discipline(self):
        # calling eb.sync() on a discipline-off workload is a no-op,
        # not an error — models can share handlers across modes
        out = run_probe(make_probe(sync_call=True, durable_sync=False), KILL)
        assert (durable_rows(out) == WRITE_VALS).all()

    def test_durable_sync_requires_durable_cols(self):
        with pytest.raises(ValueError, match="durable_sync"):
            Workload(
                name="bad", n_nodes=1, state_width=2,
                handlers=(lambda ctx: (ctx.state, ctx.emits().build()),),
                durable_sync=True,
            )


# ------------------------------------------------------- DiskFault spec
class TestDiskFaultSpec:
    def test_compile_deterministic_windows_and_targets(self):
        spec = DiskFault(
            targets=(1, 3), n_torn=2, n_sync_loss=1,
            t_min_ns=10_000, t_max_ns=20_000,
            dur_min_ns=100_000, dur_max_ns=200_000,
        )
        plan = FaultPlan((spec,))
        assert plan.slots == 6
        a = plan.compile_batch(SEEDS)
        b = plan.compile_batch(SEEDS)
        for f in ("time", "kind", "args", "valid"):
            assert np.array_equal(getattr(a, f), getattr(b, f)), f
        on = np.isin(a.kind, (KIND_TORN_ON, KIND_SYNC_LOSS))
        off = np.isin(a.kind, (KIND_TORN_OFF, KIND_SYNC_OK))
        assert (a.time[on] >= 10_000).all() and (a.time[on] < 20_000).all()
        assert (a.time[off] >= 110_000).all() and (a.time[off] < 220_000).all()
        assert np.isin(a.args[..., 0], (1, 3)).all()
        # torn windows first, sync-loss after (the spec-offset rule)
        assert a.kind[0, :4].tolist() == [
            KIND_TORN_ON, KIND_TORN_OFF, KIND_TORN_ON, KIND_TORN_OFF
        ]
        assert a.kind[0, 4:].tolist() == [KIND_SYNC_LOSS, KIND_SYNC_OK]

    def test_slot_templates_match_slots(self):
        spec = DiskFault(targets=(0, 1), n_torn=1, n_sync_loss=2)
        assert len(spec.slot_templates()) == spec.slots
        # mutators retarget by node: the template carries the target set
        assert all(t.targets == (0, 1) for t in spec.slot_templates())

    def test_validation(self):
        with pytest.raises(ValueError, match="at least one target"):
            DiskFault(targets=())
        with pytest.raises(ValueError, match="at least one torn"):
            DiskFault(targets=(0,), n_torn=0, n_sync_loss=0)
        with pytest.raises(ValueError, match="does not fit uint32"):
            DiskFault(targets=(0,), t_min_ns=0, t_max_ns=5_000_000_000)

    def test_kind_names(self):
        from madsim_tpu.chaos import kind_name

        assert kind_name(KIND_SYNC_LOSS) == "sync-loss"
        assert kind_name(KIND_TORN_ON) == "torn-on"
        ev = FaultEvent(t=1_000_000, kind=KIND_TORN_ON, a0=2)
        assert "torn-on n2" in str(ev)

    def test_disk_faults_are_noops_without_discipline(self):
        """The identity-defaults rule: DiskFault windows on a workload
        without the sync discipline change nothing but the dispatched
        chaos events themselves."""
        wl = make_probe(sync_call=False, durable_sync=False)
        plan = FaultPlan((DiskFault(
            targets=(0,), n_torn=1, n_sync_loss=1,
            t_min_ns=1_000, t_max_ns=2_000,
            dur_min_ns=1_000_000, dur_max_ns=2_000_000,
        ),))
        init = make_init(wl, CFG, plan_slots=plan.slots)
        run = jax.jit(make_run_while(wl, CFG, 60))
        out = run(init(SEEDS, plan.compile_batch(SEEDS)))
        assert (durable_rows(out) == WRITE_VALS).all()
        assert np.asarray(out.torn).shape[1] == 0


# ------------------------------- fault-window validation (satellite fix)
class TestWindowValidation:
    def test_late_window_warns(self):
        plan = FaultPlan((CrashStorm(
            targets=(1,), t_min_ns=500_000_000, t_max_ns=600_000_000,
        ),))
        with pytest.warns(UserWarning, match="cannot fire"):
            late = plan.validate_windows(100_000_000)
        assert len(late) == 1
        assert plan.validate_windows(700_000_000, warn=False) == []

    def test_search_seeds_warns_under_time_limit(self):
        wl = make_probe(sync_call=True)
        cfg = EngineConfig(pool_size=16, time_limit_ns=100_000_000)
        plan = FaultPlan((CrashStorm(
            targets=(0,), t_min_ns=200_000_000, t_max_ns=300_000_000,
        ),))
        with pytest.warns(UserWarning, match="cannot fire"):
            search_seeds(
                wl, cfg, lambda v: np.ones(8, bool), n_seeds=8,
                max_steps=60, plan=plan, require_halt=False,
            )

    def test_clamped_windows_fit_the_limit(self):
        plan = FaultPlan((
            CrashStorm(targets=(1,), t_min_ns=200_000_000,
                       t_max_ns=400_000_000),
            DiskFault(targets=(1,), t_min_ns=500_000_000,
                      t_max_ns=900_000_000),
        ))
        clamped = plan.clamped(100_000_000)
        assert clamped.validate_windows(100_000_000, warn=False) == []
        assert clamped.hash() != plan.hash()  # windows are spec identity
        rows = clamped.compile_batch(SEEDS[:8])
        on = np.isin(
            rows.kind, (KIND_KILL, KIND_TORN_ON, KIND_SYNC_LOSS)
        )
        assert (rows.time[on] < 100_000_000).all()


# ------------------------------------------------- oracle guard (satellite)
class TestOracleGuard:
    def test_extended_kind_plan_refused(self):
        from madsim_tpu.engine.oracle import run_oracle

        wl = make_raftlog(durable=True)
        plan = FaultPlan((DiskFault(targets=(0, 1)),))
        with pytest.raises(ValueError, match="two-run/two-layout"):
            run_oracle(wl, CFG, 0, 100, plan=plan)
        lit = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SYNC_LOSS, a0=0),
        ))
        with pytest.raises(ValueError, match="extended chaos kinds \\[251\\]"):
            run_oracle(wl, CFG, 0, 100, plan=lit)

    def test_base_kind_plan_also_refused(self):
        # the oracle has no plan channel at all: even base-kind plans
        # must error, not silently compare faulted vs unfaulted runs
        from madsim_tpu.engine.oracle import run_oracle

        wl = make_raftlog(durable=True)
        plan = FaultPlan((CrashStorm(targets=(1,)),))
        with pytest.raises(ValueError, match="no fault plan"):
            run_oracle(wl, CFG, 0, 100, plan=plan)


# ----------------------------------------------- FsSim power-fail semantics
def _fs_run(seed, coro_fn, time_limit=60.0):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


class TestFsPowerFail:
    def test_synced_survives_unsynced_lost(self):
        async def main():
            h = ms.Handle.current()
            node = h.create_node().ip("10.0.0.1").build()
            done = ms.SimFuture()
            result = ms.SimFuture()

            async def writer():
                f = await fs.File.create("/wal")
                await f.write_all_at(b"AAAA", 0)
                await f.sync_all()
                await f.write_all_at(b"BBBB", 4)
                done.set_result(None)
                await ms.sleep(100.0)

            node.spawn(writer())
            await done
            h.kill(node)

            async def reader():
                result.set_result(await fs.read("/wal"))

            node.spawn(reader())
            return await result

        assert _fs_run(3, main) == b"AAAA"

    def test_torn_power_fail_keeps_prefix(self):
        outcomes = set()
        for seed in range(8):
            async def main():
                h = ms.Handle.current()
                node = h.create_node().ip("10.0.0.1").build()
                done = ms.SimFuture()
                result = ms.SimFuture()

                async def writer():
                    f = await fs.File.create("/wal")
                    await f.write_all_at(b"AAAA", 0)
                    await f.sync_all()
                    await f.write_all_at(b"BBBB", 4)
                    done.set_result(None)
                    await ms.sleep(100.0)

                node.spawn(writer())
                await done
                fs.FsSim.current().set_torn(node.id)
                h.kill(node)

                async def reader():
                    result.set_result(await fs.read("/wal"))

                node.spawn(reader())
                return await result

            data = _fs_run(seed, main)
            # always the synced bytes plus a PREFIX of the torn write
            assert data[:4] == b"AAAA"
            assert b"BBBB"[: len(data) - 4] == data[4:]
            outcomes.add(data)
        assert len(outcomes) >= 2, f"tear never varied: {outcomes}"

    def test_second_power_fail_keeps_torn_fragment(self):
        """A torn fragment that reached the platter IS on-disk state: a
        second power failure (no intervening sync) must not roll it
        back — the engine commits the prefix into SimState.disk at the
        kill, and FsSim must agree (dual-mode parity)."""
        async def main():
            h = ms.Handle.current()
            node = h.create_node().ip("10.0.0.1").build()
            done = ms.SimFuture()
            result = ms.SimFuture()

            async def writer():
                f = await fs.File.create("/wal")
                await f.write_all_at(b"AAAA", 0)
                await f.sync_all()
                await f.write_all_at(b"BBBB", 4)
                done.set_result(None)
                await ms.sleep(100.0)

            node.spawn(writer())
            await done
            sim = fs.FsSim.current()
            sim.set_torn(node.id)
            h.kill(node)
            after_first = bytes(sim._nodes[node.id]["/wal"].data)
            h.restart(node)
            await ms.sleep(0.05)
            h.kill(node)  # second failure, nothing new written or synced

            async def reader():
                result.set_result(await fs.read("/wal"))

            node.spawn(reader())
            return after_first, await result

        first, second = _fs_run(11, main)
        assert second == first, (
            "a second power failure un-persisted the torn fragment"
        )

    def test_sync_loss_window_lies(self):
        async def main():
            h = ms.Handle.current()
            node = h.create_node().ip("10.0.0.1").build()
            done = ms.SimFuture()
            result = ms.SimFuture()

            async def writer():
                f = await fs.File.create("/wal")
                await f.write_all_at(b"AAAA", 0)
                await f.sync_all()  # honest: commits
                fs.FsSim.current().set_sync_loss(node.id)
                await f.write_all_at(b"BBBB", 4)
                await f.sync_all()  # lies: commits nothing
                done.set_result(None)
                await ms.sleep(100.0)

            node.spawn(writer())
            await done
            h.kill(node)

            async def reader():
                result.set_result(await fs.read("/wal"))

            node.spawn(reader())
            return await result

        assert _fs_run(5, main) == b"AAAA"

    def test_injected_write_errors(self):
        async def main():
            h = ms.Handle.current()
            node = h.create_node().ip("10.0.0.1").build()
            result = ms.SimFuture()

            async def writer():
                f = await fs.File.create("/wal")
                await f.write_all_at(b"ok", 0)
                fs.FsSim.current().set_fail_writes(node.id)
                try:
                    await f.write_all_at(b"boom", 2)
                    result.set_result("no-error")
                except OSError as e:
                    fs.FsSim.current().set_fail_writes(node.id, on=False)
                    await f.write_all_at(b"!!", 2)
                    result.set_result((e.errno, await fs.read("/wal")))

            node.spawn(writer())
            return await result

        errno, data = _fs_run(2, main)
        assert errno == 5 and data == b"ok!!"

    def test_nemesis_drives_disk_faults_into_fssim(self):
        plan = LiteralPlan(events=(
            FaultEvent(t=10_000_000, kind=KIND_SYNC_LOSS, a0=0),
            FaultEvent(t=20_000_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=30_000_000, kind=KIND_SYNC_OK, a0=0),
            FaultEvent(t=40_000_000, kind=KIND_TORN_OFF, a0=0),
        ))

        async def main():
            h = ms.Handle.current()
            node = h.create_node().ip("10.0.0.1").build()
            sim = h.simulator(fs.FsSim)
            nem = Nemesis(plan, nodes=[node])
            states = []

            async def probe():
                # sample BETWEEN the plan times (15/25/35/45 ms)
                await ms.sleep(0.015)
                for _ in range(4):
                    states.append((
                        node.id in sim._sync_loss, node.id in sim._torn
                    ))
                    await ms.sleep(0.01)

            p = node.spawn(probe())
            await nem.run()
            await p
            return states

        rt = ms.Runtime(seed=1)
        rt.set_time_limit(2.0)
        states = rt.block_on(main())
        assert states == [(True, False), (True, True), (False, True),
                          (False, False)]

    def test_nemesis_broadcast_target_hits_every_node(self):
        # the engine's a0=-1 means EVERY node (core.py 251-254); the
        # nemesis must broadcast too, not negative-index the last node
        plan = LiteralPlan(events=(
            FaultEvent(t=10_000_000, kind=KIND_SYNC_LOSS, a0=-1),
            FaultEvent(t=30_000_000, kind=KIND_SYNC_OK, a0=-1),
        ))

        async def main():
            h = ms.Handle.current()
            a = h.create_node().ip("10.0.0.1").build()
            b = h.create_node().ip("10.0.0.2").build()
            sim = h.simulator(fs.FsSim)
            mid = []

            async def probe():
                await ms.sleep(0.02)
                mid.append(set(sim._sync_loss))

            p = a.spawn(probe())
            await Nemesis(plan, nodes=[a, b]).run()
            await p
            return mid[0], set(sim._sync_loss), {a.id, b.id}

        rt = ms.Runtime(seed=4)
        rt.set_time_limit(2.0)
        mid, after, both = rt.block_on(main())
        assert mid == both, "a0=-1 must fault EVERY node's disk"
        assert after == set()

    def test_fssim_engine_convergence(self):
        """The dual-mode storage contract (the TestDualModeConvergence
        shape): the same three scenarios — synced write, unsynced
        write, torn unsynced write — produce the same recovered-state
        CLASSES in both execution modes: synced data survives, an
        unsynced write is lost, a torn write survives as a prefix."""
        # engine side, 64 seeds each
        synced = {
            tuple(map(int, r))
            for r in durable_rows(run_probe(make_probe(True), KILL))
        }
        unsynced = {
            tuple(map(int, r))
            for r in durable_rows(run_probe(make_probe(False), KILL))
        }
        torn_plan = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        torn = {
            tuple(map(int, r))
            for r in durable_rows(run_probe(make_probe(False), torn_plan))
        }
        assert synced == {WRITE_VALS}
        assert unsynced == {(0, 0, 0)}
        prefixes = {WRITE_VALS[:k] + (0,) * (3 - k) for k in range(4)}
        assert torn <= prefixes and len(torn) >= 2

        # FsSim side: the byte-level twin of the same scenarios
        # (test_synced_survives_unsynced_lost and
        # test_torn_power_fail_keeps_prefix above assert the same three
        # classes: survive / lose / prefix) — here we assert the MODES
        # AGREE on the classification for the shared scenario set
        engine_classes = {
            "synced": synced == {WRITE_VALS},
            "unsynced": unsynced == {(0, 0, 0)},
            "torn-is-prefix": torn <= prefixes,
        }
        assert all(engine_classes.values()), engine_classes


# ------------------------------------------------- recovery_safety detector
def _bh(rows):
    """BatchHistory from [(op, key, arg, client, ok), ...] per seed."""
    s = len(rows)
    h = max((len(r) for r in rows), default=1)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    count = np.zeros((s,), np.int32)
    for i, r in enumerate(rows):
        count[i] = len(r)
        for j, rec in enumerate(r):
            word[i, j] = rec
            t[i, j] = j
    return BatchHistory(word=word, t=t, count=count,
                        drop=np.zeros((s,), np.int32))


SY, RC = OP_SYNCED, OP_RECOVER


class TestRecoveryDetector:
    def test_clean_and_violating(self):
        h = _bh([
            # synced 2 then recovered 2: clean
            [(SY, 0, 2, 1, OK_OK), (RC, 0, 2, 1, OK_OK)],
            # synced 3, recovered 1: the durable state regressed
            [(SY, 0, 3, 1, OK_OK), (RC, 0, 1, 1, OK_OK)],
            # recovered MORE than synced (caught up another way): clean
            [(SY, 0, 1, 1, OK_OK), (RC, 0, 2, 1, OK_OK)],
        ])
        assert recovery_safety(h, SY, RC).tolist() == [True, False, True]

    def test_floor_is_last_sync_not_max(self):
        # a newer-term truncation legitimately shrinks the synced log:
        # sync 3, sync 2 (truncate), crash, recover 2 — clean
        h = _bh([[
            (SY, 0, 3, 1, OK_OK), (SY, 0, 2, 1, OK_OK), (RC, 0, 2, 1, OK_OK),
        ]])
        assert recovery_safety(h, SY, RC).tolist() == [True]

    def test_per_client_floors(self):
        # node 1's sync is not node 2's floor
        h = _bh([[
            (SY, 0, 5, 1, OK_OK), (RC, 0, 0, 2, OK_OK),
        ], [
            (SY, 0, 5, 1, OK_OK), (SY, 0, 1, 2, OK_OK),
            (RC, 0, 0, 1, OK_OK),
        ]])
        assert recovery_safety(h, SY, RC).tolist() == [True, False]

    def test_vacuous_histories(self):
        h = _bh([[], [(RC, 0, 0, 1, OK_OK)], [(SY, 0, 4, 1, OK_OK)]])
        assert recovery_safety(h, SY, RC).all()


# ------------------------------------------- raftlog storage certificates
RL_NODES = (0, 1, 2, 3, 4)
RL_CFG = EngineConfig(
    pool_size=128, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
)
STORE_PLAN = FaultPlan((
    CrashStorm(
        targets=RL_NODES, n=2, t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    FlappingPartition(
        targets=RL_NODES, n_cycles=2, t_min_ns=50_000_000,
        t_max_ns=400_000_000, dur_min_ns=100_000_000,
        dur_max_ns=300_000_000, up_min_ns=20_000_000, up_max_ns=200_000_000,
    ),
    DiskFault(
        targets=RL_NODES, n_torn=2, t_min_ns=50_000_000,
        t_max_ns=500_000_000,
    ),
), name="store-hunt")


def _store_inv(box):
    def inv(h):
        box["commit"] = election_safety(h, elect_op=OP_COMMIT)
        box["elect"] = election_safety(h, elect_op=OP_ELECT)
        box["recover"] = recovery_safety(
            h, sync_op=OP_SYNCED, recover_op=OP_RECOVER
        )
        return box["commit"] & box["elect"] & box["recover"]

    return inv


class TestRaftlogStorage:
    def test_mutant_validation(self):
        with pytest.raises(ValueError, match="needs durable=True"):
            make_raftlog(bug="nosync")
        with pytest.raises(ValueError, match="unknown raftlog bug"):
            make_raftlog(durable=True, bug="fsync-maybe")
        assert make_raftlog(durable=True, bug="nosync").name == "raftlog-nosync"
        assert make_raftlog(durable=True).name == "raftlog"
        assert make_raftlog(durable=True).durable_sync
        assert not make_raftlog().durable_sync

    @pytest.mark.slow
    def test_correct_sync_placement_clean_under_disk_chaos(self):
        """Crash storms + flapping partitions + torn-write windows:
        fsync-before-reply placement shows zero committed-value loss,
        zero double votes and zero recovery-safety violations (the
        soak runs this at >= 2048 seeds — STORE_r10.txt)."""
        box = {}
        rep = search_seeds(
            make_raftlog(record=True, chaos=False, durable=True),
            RL_CFG, None, n_seeds=256, max_steps=6000,
            history_invariant=_store_inv(box), plan=STORE_PLAN,
            require_halt=False,
        )
        assert rep.failing_seeds.size == 0
        assert rep.overflowed_seeds.size == 0

    @pytest.mark.slow
    def test_missing_sync_mutant_caught(self):
        """The planted acked-before-durable mutant loses committed
        values under the SAME fault space (deterministic: the engine is
        bit-stable, so the uniform sweep's finds are pinned)."""
        box = {}
        rep = search_seeds(
            make_raftlog(record=True, chaos=False, durable=True,
                         bug="nosync"),
            RL_CFG, None, n_seeds=512, max_steps=6000,
            history_invariant=_store_inv(box), plan=STORE_PLAN,
            require_halt=False,
        )
        assert rep.failing_seeds.size > 0
        bad = ~box["commit"] & ~rep.overflowed
        assert bad.any(), "the mutant's signature is committed-value loss"

    @pytest.mark.slow
    def test_lying_disk_positive_control(self):
        """SYNC_LOSS windows on the CORRECT model: the recovery-safety
        detector must see the disk lie (proof the injection works and
        the detector is live)."""
        plan = FaultPlan((
            CrashStorm(
                targets=RL_NODES, n=2, t_min_ns=150_000_000,
                t_max_ns=500_000_000, down_min_ns=100_000_000,
                down_max_ns=400_000_000,
            ),
            DiskFault(
                targets=RL_NODES, n_torn=0, n_sync_loss=3,
                t_min_ns=10_000_000, t_max_ns=400_000_000,
                dur_min_ns=200_000_000, dur_max_ns=600_000_000,
            ),
        ), name="lying-disk")
        box = {}
        rep = search_seeds(
            make_raftlog(record=True, chaos=False, durable=True),
            RL_CFG, None, n_seeds=256, max_steps=6000,
            history_invariant=lambda h: recovery_safety(
                h, sync_op=OP_SYNCED, recover_op=OP_RECOVER
            ),
            plan=plan, require_halt=False,
        )
        assert rep.failing_seeds.size > 0

    def test_explain_narrates_disk_faults(self):
        """obs.explain names the disk-fault events and counts syncs —
        a torn-write repro reads end to end (the forensics satellite)."""
        from madsim_tpu import obs

        wl = make_probe(sync_call=True)
        plan = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SYNC_LOSS, a0=0),
            FaultEvent(t=5_000_000, kind=KIND_SYNC_OK, a0=0),
            FaultEvent(t=8_000_000, kind=KIND_TORN_ON, a0=0),
            FaultEvent(t=50_000_000, kind=KIND_KILL, a0=0),
        ))
        text = obs.explain(wl, CFG, seed=7, plan=plan, max_steps=60)
        assert "SYNC_LOSS" in text and "TORN_ON" in text
        assert "sync-loss" in text  # the plan pretty-printer names too
        assert "sync=1" in text  # MET_SYNC in the counter row

    def test_perfetto_renders_disk_fault_spans(self):
        from madsim_tpu import obs

        wl = make_probe(sync_call=True)
        plan = LiteralPlan(events=(
            FaultEvent(t=1_000, kind=KIND_SYNC_LOSS, a0=0),
            FaultEvent(t=5_000_000, kind=KIND_SYNC_OK, a0=0),
            FaultEvent(t=8_000_000, kind=KIND_TORN_ON, a0=0),  # unclosed
        ))
        init = make_init(wl, CFG, plan_slots=plan.slots, timeline_cap=64)
        run = jax.jit(make_run_while(wl, CFG, 60, timeline_cap=64))
        out = run(init(SEEDS[:1], plan.compile_batch(SEEDS[:1])))
        doc = obs.to_perfetto(obs.decode_timeline(out, wl, 0), wl)
        chaos = {
            r["name"] for r in doc["traceEvents"]
            if r.get("cat") == "chaos"
        }
        assert "lying fsync n0" in chaos
        assert "torn writes n0" in chaos  # open window runs to the end
