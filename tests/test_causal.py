"""Causal provenance (ISSUE 19): device-folded happens-before clocks.

The contract under test, clause by clause:

* **Derived state.** ``causal=False`` is the pre-causal engine:
  zero-size provenance columns and bit-identical traces/pools/rings
  across the scatter/dense lowerings, the time32 representation and
  the compacted runner — turning the axis on changes what is CAPTURED,
  never what HAPPENS.
* **DAG == derivation.** The device fold writes ``seq``/``parent``/
  ``lam`` into the ring; ``obs.causal.rederive`` recomputes the
  Lamport column host-side from nothing but the decoded stream. They
  must agree row for row — the refold discipline applied to lineage.
* **Cones.** ``causal_slice`` is the backward happens-before closure:
  sound (every member's causes are members) and minimal (a pinned
  pingpong scenario where one concurrent event is provably excluded).
* **Checkpoints.** Format 10 carries the causal columns (a Lamport
  clock is history, not a pool function): a causal run snapshots and
  resumes bit-identically, and a causal-off checkpoint refuses to
  resume under a causal step with the designed shape error.
* **Perfetto arrows.** Causal captures attribute flow arrows EXACTLY
  (by parent seq); the same-timestamp fixture shows the heuristic
  fallback mis-attributing precisely the case the causal path fixes —
  and that the fallback still renders for old captures.
* **Lint/absint.** The noninterference and interval provers sweep a
  causal axis: the clock fold is isolated derived state and the
  lam/seq counters are proved overflow-free.

tools/causal_soak.py runs the same pins at evidence scale
(CAUSAL_r13.txt); the one campaign-scale identity here rides the slow
tier.
"""

import dataclasses

import numpy as np
import pytest

import jax

from madsim_tpu import explore, obs
from madsim_tpu.chaos import CrashStorm, FaultPlan, GrayFailure, PauseStorm
from madsim_tpu.check import device as dc
from madsim_tpu.check import election_safety, violation_cones
from madsim_tpu.engine import (
    EngineConfig,
    load_checkpoint,
    make_init,
    make_run,
    save_checkpoint,
    search_seeds,
)
from madsim_tpu.engine.core import (
    FIRST_USER_KIND,
    PARENT_NONE,
    PARENT_PLAN,
    time32_eligible,
)
from madsim_tpu.engine.replay import ReplayEvent
from madsim_tpu.models import make_kvchaos, make_pingpong, make_raft
from madsim_tpu.models.raft import OP_ELECT
from madsim_tpu.obs.causal import (
    causal_slice,
    derive_parents,
    parent_class,
    rederive,
)

RAFT_CFG = EngineConfig(pool_size=64, loss_p=0.02)
RAFT_PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=1),
), name="causal-test")

_ONES = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731


def _elect_inv(h):
    return election_safety(h, elect_op=OP_ELECT)


def _pingpong_events(**kw):
    """Decoded causal capture of pingpong seed 0 — the 3-node fixture
    (server + 2 clients) whose lineage the module docstring narrates."""
    wl = make_pingpong(rounds=4)
    r = search_seeds(
        wl, EngineConfig(), _ONES, n_seeds=4, max_steps=200,
        timeline_cap=256, causal=True, **kw,
    )
    return wl, obs.decode_timeline(r.timeline, wl, 0)


# ------------------------------------------------------------- identity
class TestOffIdentity:
    def test_causal_off_columns_are_zero_size(self):
        wl = make_raft()
        seeds = np.arange(4, dtype=np.uint64)
        off = make_init(wl, RAFT_CFG, timeline_cap=8)(seeds)
        on = make_init(wl, RAFT_CFG, timeline_cap=8, causal=True)(seeds)
        for f in ("lam", "ev_parent", "ev_lam", "tl_seq", "tl_parent",
                  "tl_lam"):
            assert np.asarray(getattr(off, f)).size == 0, f
            assert np.asarray(getattr(on, f)).size > 0, f
        # the clock is per (seed, node); provenance is per pool row
        assert on.lam.shape == (4, wl.n_nodes)
        assert on.ev_parent.shape == on.ev_time.shape

    def test_off_on_bit_identity_layouts_and_time32(self):
        """The fold is derived state on every lowering: same trace,
        clock, step count and pools with the axis on or off."""
        wl = make_raft()
        # the bounded-backoff config is what makes raft time32-eligible
        # (test_pool_index.py idiom)
        cfg = EngineConfig(pool_size=64, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        assert time32_eligible(wl, cfg)
        seeds = np.arange(8, dtype=np.uint64)
        for layout in ("scatter", "dense"):
            for t32 in (False, True):
                outs = {}
                for causal in (False, True):
                    init = make_init(wl, cfg, time32=t32,
                                     causal=causal)
                    run = jax.jit(make_run(
                        wl, cfg, 200, layout=layout, time32=t32,
                        causal=causal,
                    ))
                    outs[causal] = jax.block_until_ready(run(init(seeds)))
                for f in ("trace", "now", "step", "halted", "ev_time",
                          "ev_meta", "overflow"):
                    assert np.array_equal(
                        np.asarray(getattr(outs[False], f)),
                        np.asarray(getattr(outs[True], f)),
                    ), (layout, t32, f)

    def test_search_off_on_and_compact_identity(self):
        """search_seeds: causal changes no verdict and no captured
        tl_t row; the compacted runner banks identical causal columns
        to the lockstep path."""
        wl = make_raft(record=True)
        kw = dict(n_seeds=16, max_steps=600, plan=RAFT_PLAN,
                  history_invariant=_elect_inv, timeline_cap=256)
        off = search_seeds(wl, RAFT_CFG, None, **kw)
        on = search_seeds(wl, RAFT_CFG, None, causal=True, **kw)
        comp = search_seeds(wl, RAFT_CFG, None, causal=True,
                            compact=True, **kw)
        assert np.array_equal(off.traces, on.traces)
        assert np.array_equal(off.ok, on.ok)
        assert np.array_equal(off.timeline.tl_t, on.timeline.tl_t)
        assert off.lam is None
        assert not hasattr(off.timeline, "tl_seq")
        assert on.lam.shape == (16, wl.n_nodes)
        for f in ("tl_t", "tl_seq", "tl_parent", "tl_lam"):
            assert np.array_equal(
                getattr(on.timeline, f), getattr(comp.timeline, f)
            ), f
        assert np.array_equal(on.lam, comp.lam)


# ------------------------------------------------- DAG == derivation
class TestLineage:
    def test_rederive_matches_device_fold(self):
        """The captured lam column equals the host Lamport re-fold over
        the decoded stream — the device DAG and the replay derivation
        describe the same happens-before relation."""
        wl, ev = _pingpong_events()
        assert len(ev) > 10
        assert rederive(ev) == [e.lam for e in ev]
        # dispatch order IS seq order, gap-free on an un-dropped ring
        assert [e.seq for e in ev] == list(range(len(ev)))
        for i, p in enumerate(derive_parents(ev)):
            if ev[i].parent >= 0:
                assert p is not None and p < i
                # a delivery's emitter dispatched at its src node; a
                # timer's (src=-1) emitter is a dispatch at its OWN
                # node (timers are scheduled locally)
                emitter_node = ev[i].src if ev[i].src >= 0 else ev[i].node
                assert ev[p].node == emitter_node
            else:
                assert p is None

    def test_parent_sentinel_classes(self):
        # init rows: the t=0 on_init dispatches carry the init sentinel
        _, ev = _pingpong_events()
        assert ev[0].parent == PARENT_NONE
        assert parent_class(ev[0].parent) == "init"
        assert parent_class(0) == "event"
        # chaos plan rows carry the plan sentinel through the ring
        wl = make_raft(record=True)
        r = search_seeds(wl, RAFT_CFG, None, n_seeds=8, max_steps=600,
                         plan=RAFT_PLAN, history_invariant=_elect_inv,
                         timeline_cap=512, causal=True)
        classes = set()
        for s in range(8):
            for e in obs.decode_timeline(r.timeline, wl, s):
                classes.add(parent_class(e.parent))
        assert "plan" in classes
        assert PARENT_PLAN < 0  # sentinels never collide with seqs

    def test_rederive_requires_causal_capture(self):
        wl = make_raft()
        r = search_seeds(wl, RAFT_CFG, _ONES, n_seeds=4, max_steps=400,
                         timeline_cap=128)
        ev = obs.decode_timeline(r.timeline, wl, 0)
        with pytest.raises(ValueError, match="causal=True"):
            rederive(ev)
        with pytest.raises(ValueError, match="causal=True"):
            causal_slice(ev)


# --------------------------------------------------------------- cones
class TestCone:
    def test_cone_soundness_closed_under_causes(self):
        wl = make_raft(record=True)
        r = search_seeds(wl, RAFT_CFG, None, n_seeds=8, max_steps=600,
                         plan=RAFT_PLAN, history_invariant=_elect_inv,
                         timeline_cap=512, causal=True)
        ev = obs.decode_timeline(r.timeline, wl, 3)
        cone = causal_slice(ev)
        assert cone.anchor == len(ev) - 1
        member = set(cone.indices)
        parents = derive_parents(ev)
        last: dict = {}
        pred = []
        for i, e in enumerate(ev):
            pred.append(last.get(e.node))
            last[e.node] = i
        for i in member:
            for j in (parents[i], pred[i]):
                assert j is None or j in member, (i, j)
        assert cone.depth == ev[cone.anchor].lam
        assert 0 < cone.fraction <= 1.0

    def test_cone_minimality_pinned_pingpong(self):
        """Anchor event 5 (node0's delivery from client 2): its cone is
        exactly {0,1,2,3,5} — event 4 (client 1's concurrent delivery)
        is EXCLUDED, the provable-concurrency claim in miniature."""
        _, ev = _pingpong_events()
        cone = causal_slice(ev, anchor=5)
        assert cone.indices == (0, 1, 2, 3, 5)
        assert 4 not in cone.indices
        assert cone.depth == 3
        assert cone.missing_parents == 0

    def test_anchor_forms_agree(self):
        _, ev = _pingpong_events()
        by_index = causal_slice(ev, anchor=5)
        by_time = causal_slice(ev, anchor=(ev[5].time_ns, ev[5].node))
        assert by_index.indices == by_time.indices
        with pytest.raises(ValueError, match="outside the captured"):
            causal_slice(ev, anchor=len(ev))
        with pytest.raises(ValueError, match="predates the capture"):
            causal_slice(ev, anchor=(-1, 0))

    def test_violation_cones_from_device_check(self):
        """The escalation payload: every device-flagged seed gets a
        cone anchored at its last completed history record."""
        cfg = EngineConfig(pool_size=40, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        screens = (dc.stale_reads(), dc.read_your_writes(),
                   dc.monotonic_reads())
        wl = make_kvchaos(writes=5, record=True, bug=True)
        r = search_seeds(wl, cfg, None, device_check=screens,
                         n_seeds=128, max_steps=600, require_halt=False,
                         timeline_cap=512, causal=True)
        if not len(r.flagged_idx):
            pytest.skip("mutant not caught in this tiny sweep")
        cones = violation_cones(r)
        assert set(cones) == set(int(i) for i in r.flagged_idx)
        for row, cone in cones.items():
            assert cone.seed == row
            assert len(cone.indices) > 0
            assert cone.anchor in cone.indices

    def test_violation_cones_requires_flags_and_ring(self):
        wl = make_raft()
        r = search_seeds(wl, RAFT_CFG, _ONES, n_seeds=4, max_steps=400)
        with pytest.raises(ValueError, match="device_check"):
            violation_cones(r)


# --------------------------------------------------------- checkpoints
class TestCheckpoint:
    def test_causal_roundtrip_resumes_identically(self, tmp_path):
        """Save mid-run, resume: the spliced causal run equals the
        uninterrupted one — clock, provenance and ring included."""
        wl = make_raft()
        seeds = np.arange(6, dtype=np.uint64)
        init = make_init(wl, RAFT_CFG, timeline_cap=128, causal=True)
        run = jax.jit(make_run(wl, RAFT_CFG, 120, timeline_cap=128,
                               causal=True))
        mid = jax.block_until_ready(run(init(seeds)))
        p = str(tmp_path / "causal.npz")
        save_checkpoint(p, mid, RAFT_CFG)
        resumed = jax.block_until_ready(run(load_checkpoint(p, RAFT_CFG)))
        straight = jax.block_until_ready(run(run(init(seeds))))
        for f in dataclasses.fields(straight):
            assert np.array_equal(
                np.asarray(getattr(straight, f.name)),
                np.asarray(getattr(resumed, f.name)),
            ), f.name

    def test_off_checkpoint_refuses_causal_resume(self, tmp_path):
        """A causal-off snapshot has zero-size provenance columns; the
        causal step refuses it with the designed shape error instead of
        silently restarting the clock."""
        wl = make_raft()
        st = make_init(wl, RAFT_CFG, timeline_cap=8)(
            np.arange(4, dtype=np.uint64)
        )
        p = str(tmp_path / "off.npz")
        save_checkpoint(p, st, RAFT_CFG)
        run = make_run(wl, RAFT_CFG, 20, timeline_cap=8, causal=True)
        with pytest.raises(TypeError, match="causal"):
            jax.jit(run)(load_checkpoint(p, RAFT_CFG))


# ----------------------------------------------------- perfetto arrows
_K = FIRST_USER_KIND  # any user kind: the fixture only needs non-engine


def _fixture_events():
    """The same-timestamp mis-attribution case (obs/perfetto.py module
    docstring): node 1 emits at t=100us, then dispatches again at the
    DELIVERY's timestamp — the sender's-last-dispatch heuristic anchors
    the arrow at the decoy, the causal parent at the true emitter."""
    return [
        ReplayEvent(time_ns=100_000, kind=_K, node=1, src=-1,
                    args=(0, 0, 0, 0), pay=(), seq=0, parent=-1, lam=1),
        ReplayEvent(time_ns=200_000, kind=_K, node=1, src=-1,
                    args=(0, 0, 0, 0), pay=(), seq=1, parent=-1, lam=2),
        ReplayEvent(time_ns=200_000, kind=_K, node=2, src=1,
                    args=(0, 0, 0, 0), pay=(), seq=2, parent=0, lam=2),
    ]


def _flow_starts(doc):
    return [r for r in doc["traceEvents"]
            if r.get("cat") == "flow" and r["ph"] == "s"]


class TestPerfettoArrows:
    def test_causal_capture_attributes_exactly(self):
        ev = _fixture_events()
        doc = obs.to_perfetto(ev)
        (s,) = _flow_starts(doc)
        assert s["ts"] == 100.0 and s["pid"] == 1  # the true emitter
        # causal columns ride the dispatch slices' args
        rows = [r for r in doc["traceEvents"] if r.get("cat") == "dispatch"]
        assert len(rows) == len(ev)
        assert [r["args"]["seq"] for r in rows] == [0, 1, 2]
        assert rows[2]["args"]["parent"] == 0

    def test_heuristic_fallback_misattributes_the_fixture(self):
        """Strip the causal columns: the old capture still renders, and
        the arrow lands on the same-timestamp decoy — the tested reason
        the exact path exists."""
        ev = [dataclasses.replace(e, seq=-1, parent=-1, lam=0)
              for e in _fixture_events()]
        doc = obs.to_perfetto(ev)
        (s,) = _flow_starts(doc)
        assert s["ts"] == 200.0 and s["pid"] == 1  # the decoy dispatch
        rows = [r for r in doc["traceEvents"] if r.get("cat") == "dispatch"]
        assert len(rows) == len(ev)
        assert all("seq" not in r["args"] for r in rows)

    def test_emit_sidecar_middle_precedence(self):
        """emit_ns-only captures anchor at the true send time (node-
        attributed) — finer than the heuristic, coarser than causal."""
        ev = [dataclasses.replace(e, seq=-1, parent=-1, lam=0,
                                  emit_ns=(100_000 if e.src >= 0 else -1))
              for e in _fixture_events()]
        (s,) = _flow_starts(obs.to_perfetto(ev))
        assert s["ts"] == 100.0 and s["pid"] == 1

    def test_real_capture_every_arrow_exact(self):
        """On an un-dropped causal ring every delivery's arrow leaves
        its parent dispatch: arrow (pid, ts) pairs match the parent
        column exactly, arrow count equals delivery count."""
        wl, ev = _pingpong_events()
        doc = obs.to_perfetto(ev, wl, seed=0)
        starts = _flow_starts(doc)
        deliveries = [e for e in ev if e.src >= 0]
        assert len(starts) == len(deliveries)
        by_seq = {e.seq: e for e in ev}
        want = sorted(
            (by_seq[e.parent].node,
             (e.emit_ns if e.emit_ns >= 0
              else by_seq[e.parent].time_ns) / 1e3)
            for e in deliveries
        )
        got = sorted((s["pid"], s["ts"]) for s in starts)
        assert got == want


# ------------------------------------------------------- explain/fleet
class TestExplainCausal:
    def test_explain_narrates_the_cone(self):
        wl = make_raft(record=True)
        plan = FaultPlan((CrashStorm(targets=(1, 2, 3), n=1),), name="t")
        text = obs.explain(
            wl, EngineConfig(pool_size=96), seed=5, plan=plan,
            history_invariant=_elect_inv, max_steps=600, causal=True,
        )
        assert "--- causal anchor:" in text
        assert "causal cone:" in text
        assert "** ANCHOR" in text
        assert "precede the anchor" in text
        # the shared tail still narrates outcome and repro line
        assert "verdict: history invariant HOLDS" in text
        assert "repro: seed=5" in text

    def test_explain_diff_names_first_divergent_edge(self):
        wl = make_raft(record=True)
        cfg = EngineConfig(pool_size=96)
        plan = FaultPlan(
            (CrashStorm(
                targets=(0, 1, 2, 3, 4), n=2, t_min_ns=5_000_000,
                t_max_ns=60_000_000, down_min_ns=200_000_000,
                down_max_ns=400_000_000,
            ),),
            name="early",
        )
        text = obs.explain_diff(
            wl, cfg, (5, None), (5, plan),
            history_invariant=_elect_inv, max_steps=600,
            timeline_cap=1024, causal=True,
        )
        assert "first divergent causal edge: row 5" in text
        assert "clean:" in text and "violating:" in text
        # identical runs report edge identity, not a fork
        same = obs.explain_diff(
            wl, cfg, (7, None), (7, None), max_steps=600,
            timeline_cap=1024, causal=True,
        )
        assert "causal edges identical" in same


class TestFleetCausal:
    def test_fleet_reduce_depth_and_width(self):
        wl = make_raft(record=True)
        r = search_seeds(wl, RAFT_CFG, None, n_seeds=16, max_steps=600,
                         plan=RAFT_PLAN, history_invariant=_elect_inv,
                         metrics=True, causal=True)
        fm = obs.fleet_reduce(r.met, lam=r.lam)
        assert fm.depth_min is not None and fm.depth_min >= 1
        assert fm.depth_max >= fm.depth_min
        # width = sum(lam)/max(lam): 1.0 = serial, n_nodes = parallel
        assert 1.0 <= fm.width_mean <= wl.n_nodes
        assert int(fm.depth_hist.sum()) == 16
        assert "causal: depth" in fm.format()
        # per-seed depth really is the row max of the clock
        assert fm.depth_max == int(np.max(r.lam))
        off = obs.fleet_reduce(r.met)
        assert off.depth_min is None and off.width_mean is None
        assert "causal:" not in off.format()


# ---------------------------------------------------------- lint/absint
class TestLintCausal:
    def test_matrix_rows_exist(self):
        from madsim_tpu.lint.absint import ABSINT_AXES
        from madsim_tpu.lint.noninterference import (
            BUILD_AXES,
            CAMPAIGN_AXES,
        )

        assert BUILD_AXES["causal"]["causal"] is True
        assert BUILD_AXES["all"]["causal"] is True
        assert CAMPAIGN_AXES["sharded-causal"]["causal"] is True
        assert ABSINT_AXES["causal"]["causal"] is True
        assert ABSINT_AXES["all"]["causal"] is True

    def test_noninterference_causal_smoke(self):
        """The Lamport fold is isolated derived state: core outputs
        come back label-free with the causal taps on (the full matrix
        is tools/lint_soak.py's job)."""
        from madsim_tpu.lint.noninterference import check_noninterference
        from madsim_tpu.models import raft as raft_mod

        _tag, wl, cfg_kw = raft_mod.lint_entries()[0]
        rep = check_noninterference(
            wl, EngineConfig(**cfg_kw), entry="step", causal=True,
            timeline_cap=8,
        )
        assert rep.ok, rep.summary()
        assert rep.flags["causal"] is True
        assert {"lam", "ev_parent", "ev_lam"} <= set(rep.derived)

    def test_absint_causal_smoke(self):
        """lam and the dispatch-seq stamp are proved overflow-free
        under the step-budget contract — no new unproved counters."""
        from madsim_tpu.lint.absint import check_ranges
        from madsim_tpu.models import raft as raft_mod

        _tag, wl, cfg_kw, horizon = raft_mod.absint_entries()[0]
        rep = check_ranges(
            wl, EngineConfig(**cfg_kw), entry="step", causal=True,
            timeline_cap=8, horizon_ns=horizon,
        )
        assert rep.ok, rep.summary()
        assert rep.flags["causal"] is True


# ------------------------------------------------------ campaign scale
@pytest.mark.slow
class TestCampaignCausal:
    def test_host_device_identity_and_coverage_feature(self):
        """causal=True threads through both campaign drivers: host and
        device runs stay bit-identical, and the causal depth/width
        coverage features add signal the base run cannot see."""
        cfg = EngineConfig(pool_size=64, loss_p=0.02)
        plan = FaultPlan((
            PauseStorm(targets=(0, 1, 2, 3, 4), n=1,
                       t_min_ns=20_000_000, t_max_ns=300_000_000,
                       down_min_ns=50_000_000, down_max_ns=200_000_000),
            GrayFailure(targets=(0, 1, 2, 3, 4), n_links=1),
        ), name="causal-campaign")
        kw = dict(generations=3, batch=24, root_seed=11, max_steps=600,
                  cov_words=16, invariant=lambda v: v["halted"])

        def fp(rep):
            return (
                [(e.id, e.generation, e.seed, e.trace, e.new_bits)
                 for e in rep.corpus],
                rep.cov_map.tolist(), rep.curve,
            )

        host = explore.run(make_raft(), cfg, plan, causal=True, **kw)
        dev = explore.run_device(make_raft(), cfg, plan, causal=True,
                                 **kw)
        assert fp(host) == fp(dev)
        base = explore.run(make_raft(), cfg, plan, **kw)
        # generation 0 runs IDENTICAL schedules on both (uniform draws,
        # no steering yet), so the causal depth/jump feature class can
        # only ADD bits there; later generations steer differently —
        # the feature class observably changes the hunt
        assert host.curve[0] > base.curve[0]
