"""Unix-domain socket sim (beyond reference parity — sim/net/unix/ is
todo!() stubs, stream.rs:16-45)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import UnixDatagram, UnixListener, UnixStream


def run(seed, coro_fn, time_limit=120.0):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


def test_unix_stream_roundtrip_partial_reads():
    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        out = ms.SimFuture()

        async def server():
            lis = await UnixListener.bind("/tmp/app.sock")
            stream, _peer = await lis.accept()
            data = await stream.read_exact(11)
            await stream.write_all(b"pong:" + data)

        async def client():
            s = await UnixStream.connect("/tmp/app.sock")
            await s.write(b"hello")
            await s.write(b" world")
            await s.flush()
            r1 = await s.read(4)
            rest = await s.read_exact(12)
            out.set_result(r1 + rest)

        a.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await out == b"pong:hello world"
        return True

    assert run(1, main)


def test_unix_stream_half_close_eof():
    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        done = ms.SimFuture()

        async def server():
            lis = await UnixListener.bind("/run/x")
            s, _ = await lis.accept()
            chunks = []
            while True:
                c = await s.read(64)
                if not c:
                    break
                chunks.append(c)
            # write half still works after the peer's half-close
            await s.write_all(b"got:" + b"".join(chunks))

        async def client():
            s = await UnixStream.connect("/run/x")
            await s.write_all(b"abc")
            s.shutdown()  # half-close: server read EOFs, our reads live
            done.set_result(await s.read_exact(7))

        a.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await done == b"got:abc"
        return True

    assert run(2, main)


def test_unix_paths_are_node_local():
    """The same path on two nodes is two different sockets."""

    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        b = h.create_node().name("b").build()
        res = ms.SimFuture()

        async def on_a():
            await UnixListener.bind("/srv")

        async def on_b():
            await ms.sleep(0.1)
            try:
                await UnixStream.connect("/srv")
            except ConnectionRefusedError:
                res.set_result("refused")

        a.spawn(on_a())
        b.spawn(on_b())
        assert await res == "refused"
        return True

    assert run(3, main)


def test_unix_stream_eof_on_node_reset():
    """Kill closes streams exactly like the TCP sim (pipe registry)."""

    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        got = ms.SimFuture()
        server_up = ms.SimFuture()

        async def server():
            lis = await UnixListener.bind("/dying")
            server_up.set_result(True)
            s, _ = await lis.accept()
            await s.read(1)  # parked until the node dies

        async def watcher(s):
            got.set_result(await s.read(16))

        a.spawn(server())
        await server_up
        # connect from a supervisor-side task on a second node is
        # impossible (node-local); spawn the client on node a, then watch
        # its stream from the supervisor via the future
        s_fut = ms.SimFuture()

        async def client():
            s = await UnixStream.connect("/dying")
            s_fut.set_result(s)

        a.spawn(client())
        s = await s_fut
        h.kill(a.id)
        # the pipes were registered on node a; kill closed them -> EOF
        assert await s._rx.recv() is None
        got.set_result(b"")
        assert await got == b""
        return True

    assert run(4, main)


def test_unix_datagram_roundtrip_and_connect():
    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        out = ms.SimFuture()

        async def server():
            sock = await UnixDatagram.bind("/dg/server")
            data, src = await sock.recv_from()
            assert src == "/dg/client"
            await sock.send_to(b"re:" + data, src)

        async def client():
            sock = await UnixDatagram.bind("/dg/client")
            await sock.connect("/dg/server")
            await sock.send(b"ping")
            out.set_result(await sock.recv())

        a.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await out == b"re:ping"
        return True

    assert run(5, main)


def test_unix_bind_conflict_and_refused():
    async def main():
        h = ms.Handle.current()
        a = h.create_node().name("a").build()
        done = ms.SimFuture()

        async def body():
            await UnixListener.bind("/one")
            try:
                await UnixListener.bind("/one")
                done.set_result("no-error")
                return
            except OSError:
                pass
            try:
                await UnixDatagram.unbound()
                sock = await UnixDatagram.unbound()
                await sock.send_to(b"x", "/nowhere")
                done.set_result("no-error")
            except ConnectionRefusedError:
                done.set_result("ok")

        a.spawn(body())
        assert await done == "ok"
        return True

    assert run(6, main)
