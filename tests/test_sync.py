"""Deterministic sync primitives (the tokio::sync-surface analog,
SURVEY.md §2 C21)."""

import madsim_tpu as ms
from madsim_tpu import sync


def run(seed, coro_fn):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(60.0)
    return rt.block_on(coro_fn())


def test_oneshot():
    async def main():
        tx, rx = sync.oneshot()

        async def producer():
            await ms.sleep(1.0)
            tx.send(99)

        ms.spawn(producer())
        return await rx.recv()

    assert run(1, main) == 99


def test_mpsc_bounded_backpressure():
    async def main():
        tx, rx = sync.channel(capacity=2)
        sent = []

        async def producer():
            for i in range(5):
                await tx.send(i)
                sent.append(i)

        ms.spawn(producer())
        await ms.sleep(1.0)
        assert len(sent) <= 3  # 2 queued + 1 possibly in-flight
        got = [await rx.recv() for _ in range(5)]
        assert got == list(range(5))
        return True

    assert run(2, main)


def test_mpsc_close_gives_none():
    async def main():
        tx, rx = sync.unbounded_channel()
        await tx.send("a")
        tx.close()
        assert await rx.recv() == "a"
        assert await rx.recv() is None
        return True

    assert run(3, main)


def test_watch():
    async def main():
        tx, rx = sync.watch("v0")
        seen = []

        async def watcher():
            while True:
                await rx.changed()
                seen.append(rx.borrow())
                if rx.borrow() == "v2":
                    return

        jh = ms.spawn(watcher())
        await ms.sleep(0.1)
        tx.send("v1")
        await ms.sleep(0.1)
        tx.send("v2")
        await jh
        return seen

    assert run(4, main) == ["v1", "v2"]


def test_mutex_exclusion():
    async def main():
        m = sync.Mutex(0)
        trace = []

        async def worker(tag):
            async with m:
                trace.append((tag, "in"))
                await ms.sleep(1.0)
                trace.append((tag, "out"))

        for t in range(3):
            ms.spawn(worker(t))
        await ms.sleep(10.0)
        # critical sections never interleave
        for i in range(0, len(trace), 2):
            assert trace[i][0] == trace[i + 1][0]
            assert trace[i][1] == "in" and trace[i + 1][1] == "out"
        return len(trace)

    assert run(5, main) == 6


def test_rwlock_readers_shared_writer_exclusive():
    async def main():
        lock = sync.RwLock(0)
        events = []

        async def reader(tag):
            async with await lock.read() as v:
                events.append(("r", tag, v))
                await ms.sleep(1.0)

        async def writer():
            async with await lock.write() as g:
                g.value = 42
                events.append(("w", None, g.value))
                await ms.sleep(1.0)

        ms.spawn(reader(1))
        ms.spawn(reader(2))
        await ms.sleep(0.1)
        ms.spawn(writer())
        await ms.sleep(5.0)

        async with await lock.read() as v:
            assert v == 42
        # both readers entered before the writer
        assert [e[0] for e in events] == ["r", "r", "w"]
        return True

    assert run(6, main)


def test_semaphore_limits_concurrency():
    async def main():
        sem = sync.Semaphore(2)
        active = {"n": 0, "max": 0}

        async def worker():
            async with sem:
                active["n"] += 1
                active["max"] = max(active["max"], active["n"])
                await ms.sleep(1.0)
                active["n"] -= 1

        for _ in range(6):
            ms.spawn(worker())
        await ms.sleep(10.0)
        assert active["max"] == 2
        return True

    assert run(7, main)


def test_notify():
    async def main():
        n = sync.Notify()
        woke = []

        async def waiter(tag):
            await n.notified()
            woke.append(tag)

        for t in range(3):
            ms.spawn(waiter(t))
        await ms.sleep(0.1)
        n.notify_one()
        await ms.sleep(0.1)
        assert len(woke) == 1
        n.notify_waiters()
        await ms.sleep(0.1)
        assert len(woke) == 3
        return True

    assert run(8, main)


def test_barrier():
    async def main():
        b = sync.Barrier(3)
        leaders = []

        async def worker(delay):
            await ms.sleep(delay)
            leaders.append(await b.wait())

        for d in (0.1, 0.5, 1.0):
            ms.spawn(worker(d))
        await ms.sleep(2.0)
        assert sorted(leaders) == [False, False, True]
        return True

    assert run(9, main)


def test_broadcast():
    async def main():
        tx = sync.broadcast()
        r1, r2 = tx.subscribe(), tx.subscribe()
        assert tx.send("x") == 2
        assert await r1.recv() == "x"
        assert await r2.recv() == "x"
        return True

    assert run(10, main)


def test_semaphore_no_lost_wakeup():
    """release must wake all waiters: a small waiter must not be stranded
    behind a large one."""

    async def main():
        sem = sync.Semaphore(0)
        done = []

        async def big():
            await sem.acquire(2)
            done.append("big")

        async def small():
            await sem.acquire(1)
            done.append("small")

        ms.spawn(big())
        await ms.sleep(0.1)
        ms.spawn(small())
        await ms.sleep(0.1)
        sem.release(1)  # only small can proceed
        await ms.sleep(1.0)
        assert done == ["small"]
        sem.release(2)
        await ms.sleep(1.0)
        assert "big" in done
        return True

    assert run(11, main)


def test_rwlock_writer_not_starved():
    """Write-preferring: overlapping readers must not starve a writer."""

    async def main():
        lock = sync.RwLock(0)
        wrote = ms.SimFuture()

        async def reader_loop(phase):
            await ms.sleep(phase)
            for _ in range(20):
                async with await lock.read():
                    await ms.sleep(1.0)

        async def writer():
            await ms.sleep(1.2)
            async with await lock.write() as g:
                g.value = 1
                wrote.set_result(ms.now_ns())

        ms.spawn(reader_loop(0.0))
        ms.spawn(reader_loop(0.5))
        ms.spawn(writer())
        t = await wrote
        assert t < 5e9  # acquired promptly, not after 20s of reads
        return True

    assert run(12, main)
