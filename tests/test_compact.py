"""Seed-compaction runner vs the plain lockstep loop — bit-identical.

Seeds are independent rows under vmap, so banking halted rows out of the
batch must not change any row's results. These tests assert per-seed
equality of every reported field (except ``step``, the RNG coordinate —
documented divergence: lockstep keeps counting for halted rows, the
compactor stops once a row is banked; halted rows make no draws, so the
difference is unobservable).
"""

import numpy as np
import pytest

import jax

from madsim_tpu.engine import (
    EngineConfig,
    make_init,
    make_run_compacted,
    make_run_while,
)
from madsim_tpu.engine.compact import RESULT_FIELDS
from madsim_tpu.models import BENCH_SPECS

COMPARE_FIELDS = tuple(f for f in RESULT_FIELDS if f != "step")


def _run_both(name, n_seeds, max_steps, shrink=2, min_size=8):
    factory, kw, _, _ = BENCH_SPECS[name]
    wl, cfg = factory(), EngineConfig(**kw)
    init = make_init(wl, cfg)
    seeds = np.arange(n_seeds, dtype=np.uint64)
    ref = jax.jit(make_run_while(wl, cfg, max_steps))(init(seeds))
    ref = jax.block_until_ready(ref)
    out = make_run_compacted(
        wl, cfg, max_steps, shrink=shrink, min_size=min_size
    )(init(seeds))
    return ref, out


@pytest.mark.parametrize(
    "name",
    ["raft"]
    + [
        pytest.param(n, marks=pytest.mark.slow)
        for n in ["broadcast", "kvchaos"]
    ],
)
def test_compacted_equals_lockstep(name):
    """Full runs (every seed halts) across three workload families,
    including kill/restart + clog chaos (kvchaos)."""
    ref, out = _run_both(name, n_seeds=64, max_steps=2000)
    assert bool(np.asarray(ref.halted).all()), "test needs a halting run"
    for f in COMPARE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), getattr(out, f), err_msg=f
        )


def test_compacted_equals_lockstep_at_step_cap():
    """Rows still live when max_steps hits are frozen identically."""
    ref, out = _run_both("raft", n_seeds=64, max_steps=9)
    assert not bool(np.asarray(ref.halted).all()), "cap must hit mid-run"
    for f in COMPARE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), getattr(out, f), err_msg=f
        )


def test_degenerate_schedule_is_single_phase():
    """min_size >= n_seeds: one phase, still correct. n_seeds matches
    test_compacted_equals_lockstep[raft] so the lockstep reference is
    the SAME program (persistent-cache hit on a cold run)."""
    ref, out = _run_both("raft", n_seeds=64, max_steps=2000, min_size=64)
    for f in COMPARE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), getattr(out, f), err_msg=f
        )
