"""Bit-identical trace compare: batched JAX engine vs the C++ oracle.

The strongest determinism check in the framework (SURVEY.md §2.6 row 4,
§7 hard part 2): the C++ oracle (native/oracle.cpp) reimplements the
engine's integer semantics and workloads independently; for any
(workload, seed, config) both must produce the identical uint64 rolling
trace hash, virtual clock, message count and final node state. This is
what licenses trusting a 65k-seed TPU batch — each row provably equals
the reference interpreter.
"""

import shutil

import numpy as np
import pytest

import jax

from madsim_tpu.engine import EngineConfig, make_init, make_run, threefry2x32
from madsim_tpu.engine.oracle import oracle_threefry, run_oracle
from madsim_tpu.models import (
    make_broadcast,
    make_kvchaos,
    make_microbench,
    make_pingpong,
    make_raft,
    make_paxos,
    make_raftlog,
    make_twophase,
)

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)

# engine==oracle is layout-independent (the dense/scatter cross is
# itself gated by check_layouts in the default tier), so the default
# gate compares one lowering per family — scatter, the CPU-native one —
# and the dense twin rides the full tier
LAYOUTS = [pytest.param("dense", marks=pytest.mark.slow), "scatter"]


def engine_batch(wl, cfg, seeds, n_steps, layout=None):
    init = make_init(wl, cfg)
    run = jax.jit(make_run(wl, cfg, n_steps, layout=layout))
    return run(init(np.asarray(seeds, np.uint64)))


def compare(wl, cfg, seeds, n_steps, layout=None, **model_kwargs):
    out = engine_batch(wl, cfg, seeds, n_steps, layout=layout)
    for idx, seed in enumerate(seeds):
        o = run_oracle(wl, cfg, seed, n_steps, **model_kwargs)
        assert int(out.trace[idx]) == o.trace, (
            f"trace diverged for seed {seed}: "
            f"engine={int(out.trace[idx]):x} oracle={o.trace:x}"
        )
        assert int(out.now[idx]) == o.now
        assert int(out.msg_count[idx]) == o.msg_count
        assert bool(out.halted[idx]) == o.halted
        assert int(out.halt_time[idx]) == o.halt_time
        assert int(out.overflow[idx]) == o.overflow
        assert np.array_equal(np.asarray(out.node_state[idx]), o.node_state)


def test_threefry_matches_oracle():
    rng = np.random.RandomState(7)
    for _ in range(100):
        k0, k1, x0, x1 = rng.randint(0, 2**32, size=4, dtype=np.uint32)
        ja, jb = threefry2x32(k0, k1, x0, x1)
        oa, ob = oracle_threefry(int(k0), int(k1), int(x0), int(x1))
        assert (int(np.uint32(ja)), int(np.uint32(jb))) == (oa, ob)


def test_pingpong_traces_bit_identical():
    wl = make_pingpong(rounds=5)
    cfg = EngineConfig(pool_size=64)
    compare(wl, cfg, list(range(16)), 200, rounds=5)


def test_pingpong_with_loss_bit_identical():
    wl = make_pingpong(rounds=3)
    cfg = EngineConfig(pool_size=64, loss_p=0.2)
    compare(wl, cfg, list(range(8)), 150, rounds=3)


def test_microbench_traces_bit_identical():
    wl = make_microbench(rounds=200)
    cfg = EngineConfig(pool_size=16)
    compare(wl, cfg, list(range(8)), 220, rounds=200)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_raft_traces_bit_identical(layout):
    # both lowerings of the step (the TPU dense form and the CPU scatter
    # form) must match the oracle bit-for-bit
    wl = make_raft()
    cfg = EngineConfig(pool_size=128, loss_p=0.05)
    compare(wl, cfg, list(range(16)), 400, layout=layout)


def test_raft_with_time_limit_bit_identical():
    wl = make_raft()
    cfg = EngineConfig(pool_size=128, time_limit_ns=200_000_000)
    compare(wl, cfg, [3, 9, 27], 400)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_broadcast_traces_bit_identical(layout):
    # partition chaos + packet loss: the clog/unclog + retransmit path
    # (the only oracle workload exercising the clogged-reschedule
    # branch, so both lowerings must run it)
    wl = make_broadcast(rounds=3)
    cfg = EngineConfig(pool_size=128, loss_p=0.05)
    compare(wl, cfg, list(range(12)), 400, layout=layout, rounds=3)


def test_broadcast_no_partition_bit_identical():
    wl = make_broadcast(rounds=2, partition=False)
    cfg = EngineConfig(pool_size=128)
    compare(wl, cfg, list(range(6)), 250, rounds=2, partition=False)


def test_kvchaos_traces_bit_identical():
    # kill/restart chaos + loss: epoch gating, restart re-init, rejoin
    wl = make_kvchaos(writes=5)
    cfg = EngineConfig(pool_size=128, loss_p=0.02)
    compare(wl, cfg, list(range(12)), 500, writes=5)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_kvchaos_payload_traces_bit_identical(layout):
    # the payload arena: client-drawn value words ride WRITE/REPL events
    # and feed the trace hash — a payload divergence anywhere fails here
    wl = make_kvchaos(writes=5, payload=True)
    cfg = EngineConfig(pool_size=128, loss_p=0.02)
    compare(wl, cfg, list(range(12)), 500, layout=layout, writes=5)


def test_kvchaos_payload_no_chaos_bit_identical():
    wl = make_kvchaos(writes=4, chaos=False, payload=True)
    cfg = EngineConfig(pool_size=128)
    compare(wl, cfg, list(range(6)), 400, writes=4, chaos=False)


def test_big_seed_values():
    # seeds above 2^32 exercise the k1 half of the key
    wl = make_pingpong(rounds=3)
    cfg = EngineConfig(pool_size=64)
    seeds = [2**63 - 1, 2**40 + 17, 123456789012345]
    compare(wl, cfg, seeds, 150, rounds=3)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_twophase_traces_bit_identical(layout):
    # 2PC: stored votes, phase-aware retransmits, participant
    # kill/restart — the sixth oracle-verified protocol family
    wl = make_twophase(txns=4)
    cfg = EngineConfig(pool_size=64, loss_p=0.03)
    compare(wl, cfg, list(range(12)), 500, layout=layout, txns=4)


def test_twophase_no_chaos_bit_identical():
    wl = make_twophase(txns=3, chaos=False)
    cfg = EngineConfig(pool_size=64, loss_p=0.05)
    compare(wl, cfg, list(range(8)), 400, txns=3, chaos=False)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_raftlog_traces_bit_identical(layout):
    # raft log replication + leader crash — the seventh oracle-verified
    # protocol family (payload arena carries the full log in appends)
    wl = make_raftlog()
    cfg = EngineConfig(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    compare(wl, cfg, list(range(12)), 3000, layout=layout)


def test_raftlog_no_chaos_bit_identical():
    wl = make_raftlog(chaos=False, n_writes=3)
    cfg = EngineConfig(pool_size=64, loss_p=0.05)
    compare(wl, cfg, list(range(8)), 2000, chaos=False, n_writes=3)


def test_raftlog_durable_bit_identical():
    # crash-recovery raft: (term, votedFor, log) survive the leader
    # kill/restart via Workload.durable_cols — the restart path restores
    # only the volatile columns, mirrored in the oracle (the durable set
    # is pushed generically by engine/oracle.py, no model flag needed)
    wl = make_raftlog(durable=True)
    cfg = EngineConfig(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    compare(wl, cfg, list(range(10)), 3000)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_paxos_traces_bit_identical(layout):
    # single-decree paxos + proposer crash — the eighth oracle-verified
    # protocol family (dueling proposers, NACK fast-forward)
    wl = make_paxos()
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    compare(wl, cfg, list(range(12)), 400, layout=layout)


def test_paxos_no_chaos_bit_identical():
    wl = make_paxos(chaos=False, n_acceptors=3, n_proposers=2)
    cfg = EngineConfig(pool_size=64, loss_p=0.05)
    compare(
        wl, cfg, list(range(8)), 400,
        chaos=False, n_acceptors=3, n_proposers=2,
    )


def test_paxos_durable_acceptors_bit_identical():
    # acceptor kills with durable (promised, accepted) columns — the
    # Workload.durable_cols restart path, mirrored in the oracle
    wl = make_paxos(durable_acceptors=True)
    cfg = EngineConfig(pool_size=64, loss_p=0.02)
    compare(wl, cfg, list(range(10)), 400, durable_acceptors=True)


@pytest.mark.parametrize("layout", LAYOUTS)
def test_snapshot_traces_bit_identical(layout):
    from madsim_tpu.models import make_snapshot

    wl = make_snapshot()
    cfg = EngineConfig(pool_size=96)
    compare(wl, cfg, list(range(12)), 400, layout=layout)


def test_snapshot_small_cluster_bit_identical():
    from madsim_tpu.models import make_snapshot

    kw = dict(n_nodes=3, n_sends=4, balance=500, amount_max=50)
    wl = make_snapshot(**kw)
    cfg = EngineConfig(pool_size=64)
    compare(wl, cfg, list(range(8)), 300, **kw)
