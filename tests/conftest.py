"""Test-session environment.

JAX tests run on a virtual 8-device CPU platform so multi-chip sharding
(seed-axis jit/shard_map over a Mesh) is exercised without TPU hardware;
the driver separately dry-runs the multi-chip path via __graft_entry__.py
and benches on the real chip.

The platform override uses jax.config.update because the environment may
pin JAX_PLATFORMS to a TPU plugin via sitecustomize (env vars alone are
not enough); the XLA flag must still be set before the backend
initializes, hence both happen here before any test imports jax.
"""

import os
import sys

# test_aio_interpose.py exercises stdlib surfaces that only exist on
# 3.11+ (asyncio.TaskGroup / asyncio.timeout) and uses `except*`, which
# is a SyntaxError before 3.11 — on older interpreters the file must be
# excluded at collection time, not skipped at runtime.
collect_ignore = []
if sys.version_info < (3, 11):
    collect_ignore.append("test_aio_interpose.py")

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's cost is dominated by
# recompiles of the engine step across parameterized cases and repeat
# runs (test_engine.py alone was ~405 s cold). The cache survives
# across pytest invocations, so `make check` pays compile cost once.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
