"""Test-session environment.

JAX tests run on a virtual 8-device CPU platform so multi-chip sharding
(seed-axis shard_map over a Mesh) is exercised without TPU hardware; the
driver separately dry-runs the multi-chip path via __graft_entry__.py.
Must be set before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
