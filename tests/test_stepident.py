"""Trace-identity pins for the PR-8 step refactor (tools/step_goldens.py).

The refactored step (rank-matched placement, per-dispatch batched RNG,
cold-bank appends) promises bit-identical VALUES to the pre-refactor
engine. These tests recompute full-SimState digests of the recorded
models — metrics + timeline + coverage/hit-counts + latency on, army
plans where the model has a client surface — and compare them to
digests captured from the PR-7-tip engine. Any value drift in the step
function fails here with the scenario name, before it can reach a
soak or an oracle run.

Tier-1 keeps the two leanest high-coverage pins (raftlog's army
scenario — chaos kinds + client rows + every observability column —
on the rank-placement scatter layout and on dense); the forced
scatter-store placement, the compacted runner and the full scenario
matrix are ``slow`` — tier-1 runs ~650s of its 870s budget on a good
box phase and this container drifts ~1.8x, so every tier-1 compile
must earn its seat (ROADMAP budget note).
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))

import step_goldens  # noqa: E402

from _step_goldens import GOLDENS  # noqa: E402


def _check(name, layout=None, compact=False, **kw):
    wl, cfg, plan, lat = step_goldens.scenarios()[name]
    got = step_goldens.run_scenario(
        name, wl, cfg, plan, lat, layout=layout, compact=compact, **kw
    )
    key = f"{name}/compact" if compact else name
    assert got == GOLDENS[key], (
        f"{key} (layout={layout}): step values drifted from the "
        f"pre-refactor engine"
    )


class TestStepIdentityLean:
    """The tier-1 pins: the heaviest surface, both layouts."""

    def test_raftlog_army_scatter_rank(self):
        _check("raftlog/army-obs", layout="scatter")

    def test_raftlog_army_dense(self):
        _check("raftlog/army-obs", layout="dense")

    def test_raftlog_army_pool_indexed(self):
        # the readiness-partitioned pool (ISSUE 13) against the SAME
        # pre-refactor digests: the tile summaries are excluded from
        # the digest (derived by construction), so the indexed program
        # must reproduce every other SimState field bit-for-bit
        _check("raftlog/army-obs", layout="scatter", pool_index=True)


@pytest.mark.slow
class TestStepIdentityPlacements:
    """The other two lowerings of the same scenario: the forced
    scatter-store placement (the large-pool program) and the compacted
    runner — redundant with the matrix below but kept addressable."""

    def test_raftlog_army_scatter_store(self):
        _check("raftlog/army-obs", layout="scatter", placement="scatter")

    def test_raftlog_army_compacted(self):
        _check("raftlog/army-obs", compact=True)


@pytest.mark.slow
class TestStepIdentityMatrix:
    """Every captured scenario, every lowering (the full safety net)."""

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_scatter(self, name):
        _check(name, layout="scatter")

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_pool_indexed(self, name):
        # the indexed pool with element-store writes (the default
        # under the index) on every captured scenario
        _check(name, layout="scatter", pool_index=True)

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_pool_indexed_rank_chains(self, name):
        # the within-tile select-chain write lowering
        _check(name, layout="scatter", pool_index=True, placement="rank")

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_pool_indexed_compacted(self, name):
        _check(name, compact=True, pool_index=True)

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_scatter_store_placement(self, name):
        _check(name, layout="scatter", placement="scatter")

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_dense(self, name):
        _check(name, layout="dense")

    @pytest.mark.parametrize("name", sorted(step_goldens.scenarios()))
    def test_compacted(self, name):
        _check(name, compact=True)
