"""TCP/UDP stream sims and the filesystem simulator (SURVEY.md §2
C13/C14/C17)."""

import pytest

import madsim_tpu as ms
from madsim_tpu import fs
from madsim_tpu.net import NetSim, TcpListener, TcpStream, UdpSocket


def run(seed, coro_fn, time_limit=120.0):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


def two_nodes(h):
    a = h.create_node().name("a").ip("10.0.0.1").build()
    b = h.create_node().name("b").ip("10.0.0.2").build()
    return a, b


def test_tcp_roundtrip_partial_reads():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        out = ms.SimFuture()

        async def server():
            lis = await TcpListener.bind("0.0.0.0:80")
            stream, peer = await lis.accept()
            data = await stream.read_exact(11)
            await stream.write_all(b"pong:" + data)

        async def client():
            s = await TcpStream.connect("10.0.0.2:80")
            await s.write(b"hello")  # buffered, not sent
            await s.write(b" world")
            await s.flush()  # sent as one chunk
            r1 = await s.read(4)
            rest = await s.read_exact(12)
            out.set_result(r1 + rest)

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await out == b"pong:hello world"
        return True

    assert run(1, main)


def test_tcp_eof_on_node_reset():
    """Reference tcp/mod.rs:176-208: node reset => EOF on the stream."""

    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        got = ms.SimFuture()

        async def server():
            lis = await TcpListener.bind("0.0.0.0:80")
            stream, _ = await lis.accept()
            await stream.read(1)  # hold

        async def client():
            s = await TcpStream.connect("10.0.0.2:80")
            r = await s.read(10)  # blocks until server dies
            got.set_result(r)

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        await ms.sleep(2.0)
        h.kill(b)
        assert await got == b""
        return True

    assert run(2, main)


def test_tcp_partition_and_recovery():
    async def main():
        h = ms.Handle.current()
        net = h.simulator(NetSim)
        a, b = two_nodes(h)
        received = []

        async def server():
            lis = await TcpListener.bind("0.0.0.0:80")
            stream, _ = await lis.accept()
            while True:
                chunk = await stream.read(1024)
                if not chunk:
                    return
                received.append(chunk)

        async def client():
            s = await TcpStream.connect("10.0.0.2:80")
            await s.write_all(b"one")
            await ms.sleep(2.0)
            await s.write_all(b"two")  # sent while partitioned

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        await ms.sleep(1.0)
        net.clog_link(a, b)
        await ms.sleep(10.0)
        assert received == [b"one"]
        net.unclog_link(a, b)
        await ms.sleep(15.0)
        assert received == [b"one", b"two"]
        return True

    assert run(3, main)


def test_udp_datagrams():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        got = ms.SimFuture()

        async def server():
            sock = await UdpSocket.bind("0.0.0.0:53")
            data, src = await sock.recv_from()
            await sock.send_to(b"resp:" + data, src)

        async def client():
            sock = await UdpSocket.bind("0.0.0.0:0")
            await sock.connect("10.0.0.2:53")
            await sock.send(b"query")
            got.set_result(await sock.recv())

        b.spawn(server())
        await ms.sleep(0.1)
        a.spawn(client())
        assert await got == b"resp:query"
        return True

    assert run(4, main)


def test_fs_read_write_metadata():
    async def main():
        h = ms.Handle.current()
        node = h.create_node().ip("10.0.0.1").build()
        done = ms.SimFuture()

        async def work():
            f = await fs.File.create("/data/log")
            await f.write_all_at(b"hello", 0)
            await f.write_all_at(b"world", 5)
            assert await f.read_at(10, 0) == b"helloworld"
            assert (await f.metadata()).len == 10
            await f.set_len(5)
            assert await fs.read("/data/log") == b"hello"
            with pytest.raises(FileNotFoundError):
                await fs.File.open("/missing")
            done.set_result(True)

        node.spawn(work())
        return await done

    assert run(5, main)


def test_fs_is_per_node():
    async def main():
        h = ms.Handle.current()
        a, b = two_nodes(h)
        done = ms.SimFuture()

        async def on_a():
            await fs.write("/shared", b"from-a")

        async def on_b():
            try:
                await fs.read("/shared")
                done.set_result("visible")
            except FileNotFoundError:
                done.set_result("isolated")

        a.spawn(on_a())
        await ms.sleep(0.5)
        b.spawn(on_b())
        return await done

    assert run(6, main) == "isolated"


def test_fs_power_failure_drops_unsynced():
    """Power failure (node reset) rolls files back to the last sync_all —
    the intended semantics of reference fs.rs:51."""

    async def main():
        h = ms.Handle.current()
        node = h.create_node().ip("10.0.0.1").build()
        phase1 = ms.SimFuture()
        result = ms.SimFuture()

        async def writer():
            f = await fs.File.create("/db")
            await f.write_all_at(b"durable", 0)
            await f.sync_all()
            await f.write_all_at(b"volatile", 7)
            phase1.set_result(None)
            await ms.sleep(100.0)

        node.spawn(writer())
        await phase1
        h.kill(node)  # power failure

        async def reader():
            result.set_result(await fs.read("/db"))

        node.spawn(reader())
        return await result

    assert run(7, main) == b"durable"
