"""The asyncio compat shim (madsim-tokio analog): asyncio-written code
runs deterministically inside the simulator and delegates to the real
asyncio outside (reference madsim-tokio/src/lib.rs cfg switch)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.compat import asyncio as aio


def run(seed, coro_fn, time_limit=60.0):
    rt = ms.Runtime(seed=seed)
    rt.set_time_limit(time_limit)
    return rt.block_on(coro_fn())


def test_sleep_uses_virtual_time():
    async def main():
        t0 = ms.now_ns()
        await aio.sleep(5.0)
        return (ms.now_ns() - t0) / 1e9

    waited = run(1, main)
    assert waited >= 5.0


def test_create_task_gather():
    async def main():
        async def work(i):
            await aio.sleep(0.01 * i)
            return i * 10

        t = aio.create_task(work(1))
        assert not t.done()
        results = await aio.gather(work(2), work(3))
        assert results == [20, 30]
        assert await t == 10
        return True

    assert run(2, main)


def test_wait_for_timeout():
    async def main():
        with pytest.raises(aio.TimeoutError):
            await aio.wait_for(aio.sleep(10), timeout=0.5)
        # and succeeds inside the budget
        assert await aio.wait_for(aio.sleep(0.1, "done"), timeout=5) == "done"
        return True

    assert run(3, main)


def test_wait_first_completed():
    async def main():
        async def fast():
            await aio.sleep(0.1)
            return "fast"

        async def slow():
            await aio.sleep(9.0)
            return "slow"

        done, pending = await aio.wait(
            [fast(), slow()], return_when=aio.FIRST_COMPLETED
        )
        assert len(done) == 1 and len(pending) == 1
        assert next(iter(done)).result() == "fast"
        for p in pending:
            p.cancel()
        return True

    assert run(4, main)


def test_queue_producer_consumer():
    async def main():
        q = aio.Queue(maxsize=2)
        got = []

        async def producer():
            for i in range(6):
                await q.put(i)

        async def consumer():
            for _ in range(6):
                got.append(await q.get())

        p = aio.create_task(producer())
        c = aio.create_task(consumer())
        await p
        await c
        assert got == list(range(6))
        # bounded: put_nowait raises when full
        q2 = aio.Queue(maxsize=1)
        q2.put_nowait(1)
        with pytest.raises(aio.QueueFull):
            q2.put_nowait(2)
        return True

    assert run(5, main)


def test_queue_join_task_done_contract():
    """The real asyncio contract: join() blocks on the unfinished-task
    count (every put needs a matching task_done), not queue emptiness —
    the semantics madsim-tokio keeps exact by reusing real tokio sync
    (madsim-tokio/src/lib.rs:39-52)."""

    async def main():
        q = aio.Queue()
        done = []

        async def producer():
            for i in range(8):
                await q.put(i)

        async def consumer():
            while True:
                item = await q.get()
                await aio.sleep(0.01)  # work happens after get()
                done.append(item)
                q.task_done()

        await producer()  # canonical pattern: fill, then join
        workers = [aio.create_task(consumer()) for _ in range(3)]
        await q.join()  # must wait for the post-get work, not just drain
        assert sorted(done) == list(range(8))
        assert q.empty()
        for w in workers:
            w.cancel()
        # join returns immediately once the count is zero
        await q.join()
        # task_done beyond the put count is an error
        with pytest.raises(ValueError):
            q.task_done()
        return True

    assert run(7, main)


def test_priority_and_lifo_queue():
    async def main():
        pq = aio.PriorityQueue()
        for x in (3, 1, 2):
            pq.put_nowait(x)
        assert [pq.get_nowait() for _ in range(3)] == [1, 2, 3]
        lq = aio.LifoQueue()
        for x in (1, 2, 3):
            lq.put_nowait(x)
        assert [lq.get_nowait() for _ in range(3)] == [3, 2, 1]
        return True

    assert run(6, main)


def test_lock_event_semaphore():
    async def main():
        lock = aio.Lock()
        order = []

        async def worker(i):
            async with lock:
                order.append(("enter", i))
                await aio.sleep(0.1)
                order.append(("exit", i))

        await aio.gather(worker(1), worker(2))
        # mutual exclusion: enter/exit strictly paired
        assert order[0][0] == "enter" and order[1] == ("exit", order[0][1])

        ev = aio.Event()
        seen = []

        async def waiter():
            await ev.wait()
            seen.append(True)

        t = aio.create_task(waiter())
        await aio.sleep(0.05)
        assert not seen
        ev.set()
        await t
        assert seen == [True]

        sem = aio.BoundedSemaphore(1)
        async with sem:
            assert sem.locked()
        with pytest.raises(ValueError):
            sem.release()
        return True

    assert run(7, main)


def test_shim_is_deterministic():
    def scenario(seed):
        events = []

        async def main():
            q = aio.Queue()

            async def noisy(i):
                await aio.sleep(ms.random() * 0.1)
                await q.put(i)

            for i in range(5):
                aio.create_task(noisy(i))
            for _ in range(5):
                events.append((await q.get(), round(ms.now_ns() / 1e6, 3)))

        run(seed, main)
        return events

    assert scenario(11) == scenario(11)
    assert scenario(11) != scenario(12)


def test_outside_sim_delegates_to_real_asyncio():
    import asyncio as real

    async def main():
        await aio.sleep(0)
        t = aio.create_task(aio.sleep(0, "x"))
        return await t

    assert real.run(main()) == "x"
    # sync primitives constructed outside a sim are the real classes
    assert isinstance(aio.Queue(), real.Queue)
    assert isinstance(aio.Lock(), real.Lock)


def test_install_uninstall():
    import sys

    from madsim_tpu import compat

    compat.install()
    try:
        import asyncio

        assert asyncio is aio
    finally:
        compat.uninstall()
    import asyncio

    assert asyncio is not aio


def test_timeout_context_manager_interrupts_blocked_body():
    """`async with asyncio.timeout(..)` must cancel a body blocked on an
    await that never resolves (the liveness-guard use case)."""

    async def main():
        import madsim_tpu as ms_

        hung = ms_.SimFuture(name="never")
        t0 = ms_.now_ns()
        with pytest.raises(aio.TimeoutError):
            async with aio.timeout(2.0):
                await hung
        waited = (ms_.now_ns() - t0) / 1e9
        assert 2.0 <= waited < 3.0
        # a body that finishes in time is unaffected, and the disarmed
        # timer never fires into later awaits
        async with aio.timeout(5.0):
            await aio.sleep(0.1)
        await aio.sleep(10.0)
        return True

    assert run(8, main)
