"""engine/vmem.py — the VMEM-resident pallas runner.

The kernel body IS make_step, so the only thing that can diverge is
the wrapping (blocking, table threading, zero-size field rebuild);
these tests pin per-field equality with the plain runner, across
blocks, payload widths and chaos.
"""

import dataclasses

import numpy as np
import pytest

import jax

from madsim_tpu.engine import EngineConfig, SimState, make_init, make_run
from madsim_tpu.engine.vmem import make_run_vmem
from madsim_tpu.models import make_kvchaos, make_raft

FIELDS = [f.name for f in dataclasses.fields(SimState)]


def assert_states_equal(a, b):
    for f in FIELDS:
        fa, fb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(fa, fb), f"field {f} diverged"


# blocks=4 (the gridded case) subsumes the single-block mechanics;
# blocks=1 rides the full tier
@pytest.mark.parametrize(
    "blocks", [pytest.param(1, marks=pytest.mark.slow), 4]
)
def test_vmem_runner_matches_plain(blocks):
    wl = make_raft()
    cfg = EngineConfig(pool_size=40, loss_p=0.02)
    n = 32 * blocks
    st = make_init(wl, cfg)(np.arange(n, dtype=np.uint64))
    plain = jax.jit(make_run(wl, cfg, 60))(st)
    vmem = make_run_vmem(wl, cfg, 60, block_seeds=32)(st)
    assert_states_equal(plain, vmem)


@pytest.mark.slow
def test_vmem_runner_with_payload_and_chaos():
    # kvchaos-payload: nonzero ev_pay exercises the full field set
    wl = make_kvchaos(writes=4, payload=True)
    cfg = EngineConfig(pool_size=64, loss_p=0.05)
    st = make_init(wl, cfg)(np.arange(48, dtype=np.uint64))
    plain = jax.jit(make_run(wl, cfg, 120))(st)
    vmem = make_run_vmem(wl, cfg, 120, block_seeds=16)(st)
    assert_states_equal(plain, vmem)


def test_vmem_rejects_unsplittable_batch():
    wl = make_raft()
    cfg = EngineConfig(pool_size=40)
    st = make_init(wl, cfg)(np.arange(40, dtype=np.uint64))
    with pytest.raises(ValueError, match="blocks"):
        make_run_vmem(wl, cfg, 10, block_seeds=32)(st)
