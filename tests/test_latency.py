"""Tail-latency observability: client army, on-device sketches, SLO
detection, and the emit-time timeline sidecar.

Contracts pinned here:

* the latency tap is DERIVED state — ``latency=None`` runs are
  bit-identical to tap-on runs, across dense/scatter/compact, and the
  army's arrival schedule is a pure function of the seed (open loop);
* the per-seed log-linear sketch is EXACTLY mergeable (fleet sketch ==
  sketch of the concatenated per-op latencies) and its quantiles match
  exact numpy quantiles within one bucket of rank error;
* ``check.slo_bounded`` flags provable per-window p99 breaches only;
* the emit-time sidecar anchors Perfetto flow arrows at the true send
  time and never perturbs the certified trace refold;
* checkpoint format 9 round-trips the new columns.
"""

import dataclasses

import numpy as np
import pytest

from madsim_tpu import check, obs
from madsim_tpu.chaos import ClientArmy, FaultPlan, GrayFailure, Nemesis
from madsim_tpu.chaos.plan import stack_plan_rows
from madsim_tpu.engine import (
    EngineConfig,
    LatencySpec,
    lat_bucket,
    load_checkpoint,
    make_init,
    make_run,
    save_checkpoint,
    search_seeds,
    user_kind,
)
from madsim_tpu.engine.core import N_LAT_BUCKETS
from madsim_tpu.models import kvchaos as KV

N_OPS = 16
N_SEEDS = 8
MAX_STEPS = 1500

WL = KV.make_kvchaos(writes=12, n_replicas=2, chaos=False, army=True)
ARMY = KV.client_army(
    n_ops=N_OPS, t_min_ns=5_000_000, t_max_ns=280_000_000, n_replicas=2
)
PLAN = FaultPlan(
    (ARMY, GrayFailure(targets=(0, 3), n_links=1, mult_min=6, mult_max=12)),
    name="latency-test",
)
CFG = EngineConfig(pool_size=64, time_limit_ns=450_000_000)
SPEC = LatencySpec(ops=N_OPS, phases=3, phase_ns=1 << 27)

_ONES = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731

_KW = dict(n_seeds=N_SEEDS, max_steps=MAX_STEPS, plan=PLAN,
           require_halt=False)


@pytest.fixture(scope="module")
def reports():
    """One sweep per (layout/compact, tap) combination — every test
    reads these, so the module costs a handful of compiles total."""
    r_off = search_seeds(WL, CFG, _ONES, layout="scatter", **_KW)
    r_sc = search_seeds(WL, CFG, _ONES, layout="scatter", latency=SPEC, **_KW)
    r_de = search_seeds(WL, CFG, _ONES, layout="dense", latency=SPEC, **_KW)
    r_co = search_seeds(WL, CFG, _ONES, compact=True, latency=SPEC, **_KW)
    return r_off, r_sc, r_de, r_co


@pytest.fixture(scope="module")
def lat_state():
    """The raw final state (per-op columns included) of the scatter run."""
    import jax

    from madsim_tpu.engine import make_run_while

    seeds = np.arange(N_SEEDS, dtype=np.uint64)
    init = make_init(WL, CFG, plan_slots=PLAN.slots, latency=SPEC)
    run = jax.jit(make_run_while(WL, CFG, MAX_STEPS, latency=SPEC))
    return jax.block_until_ready(
        run(init(seeds, PLAN.compile_batch(seeds, wl=WL)))
    )


class TestClientArmy:
    def test_compiles_deterministically_to_client_rows(self):
        ev1 = PLAN.compile(7)
        ev2 = PLAN.compile(7)
        assert ev1 == ev2
        ops = [e for e in ev1 if e.kind == ARMY.kind]
        assert len(ops) == N_OPS
        assert all(e.node == 3 for e in ops)  # the kvchaos client node
        assert sorted(e.a0 for e in ops) == list(range(N_OPS))
        assert all(
            ARMY.t_min_ns <= e.t < ARMY.t_max_ns for e in ops
        )
        # a different seed draws different arrivals (open-loop per seed)
        assert [e.t for e in PLAN.compile(8) if e.kind == ARMY.kind] != [
            e.t for e in ops
        ]

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="user kind"):
            ClientArmy(node=0, kind=3)  # an engine kind is not a surface
        with pytest.raises(ValueError, match="n_ops"):
            ClientArmy(node=0, kind=user_kind(0), n_ops=0)
        with pytest.raises(ValueError, match="targets node 9"):
            FaultPlan((ClientArmy(node=9, kind=ARMY.kind),)).compile_batch(
                np.arange(2, dtype=np.uint64), wl=WL
            )

    def test_literalize_round_trips_node(self):
        lit = PLAN.literalize(3, wl=WL)
        assert any(e.node == 3 for e in lit.events)
        rt = type(lit).from_dict(lit.to_dict())
        assert rt.events == lit.events
        # the literal replays the FaultPlan run bit-identically,
        # including the army rows (the explore corpus-entry path);
        # layout pinned so the module's compiled-run cache entry is hit
        r_plan = search_seeds(
            WL, CFG, _ONES, seeds=np.asarray([3], np.uint64),
            max_steps=MAX_STEPS, plan=PLAN, require_halt=False,
            layout="scatter",
        )
        r_lit = search_seeds(
            WL, CFG, _ONES, seeds=np.asarray([3], np.uint64),
            max_steps=MAX_STEPS, plan_rows=stack_plan_rows([lit]),
            require_halt=False, layout="scatter",
        )
        assert r_plan.traces[0] == r_lit.traces[0]

    def test_nemesis_rejects_army_rows(self):
        ev = PLAN.compile(0)
        op = next(e for e in ev if e.kind == ARMY.kind)
        with pytest.raises(ValueError, match="client-army"):
            Nemesis(PLAN)._apply(None, op)

    def test_mis_sized_army_rejected_at_sweep_entry(self):
        """An army whose op ids exceed LatencySpec.ops is a build
        error: every out-of-range marker would silently drop (lat_drop
        counts it, but the sweep refuses the whole mis-sizing)."""
        with pytest.raises(ValueError, match="exceed LatencySpec.ops"):
            search_seeds(
                WL, CFG, _ONES, plan=PLAN, n_seeds=2, max_steps=10,
                require_halt=False, latency=LatencySpec(ops=N_OPS - 1),
            )

    def test_army_requires_the_client_surface(self):
        """An army composed with a workload built WITHOUT the client
        surface must error at compile, not silently dispatch the
        clamped last handler with army args."""
        no_army = KV.make_kvchaos(writes=4, n_replicas=2, chaos=False)
        with pytest.raises(ValueError, match="client surface"):
            PLAN.compile_batch(np.arange(2, dtype=np.uint64), wl=no_army)
        lit = PLAN.literalize(0, wl=WL)
        with pytest.raises(ValueError, match="client surface"):
            lit.compile_batch(np.arange(2, dtype=np.uint64), wl=no_army)

    def test_ops_resume_after_client_restart(self):
        """Army rows ride the any-epoch sentinel: a kill+restart of the
        client drops only the ops arriving while it is DOWN — load
        resumes on the new incarnation instead of silently zeroing for
        the rest of the run (which would make crash-the-client
        schedules read as vacuously SLO-clean)."""
        from madsim_tpu.chaos import FaultEvent, LiteralPlan
        from madsim_tpu.engine import KIND_KILL, KIND_RESTART

        lit = LiteralPlan(events=(
            FaultEvent(t=50_000_000, kind=ARMY.kind, a0=0, node=3),
            FaultEvent(t=150_000_000, kind=ARMY.kind, a0=1, node=3),
            FaultEvent(t=300_000_000, kind=ARMY.kind, a0=2, node=3),
            FaultEvent(t=100_000_000, kind=KIND_KILL, a0=3),
            FaultEvent(t=200_000_000, kind=KIND_RESTART, a0=3),
        ), name="client-crash")
        r = search_seeds(
            WL, CFG, _ONES, plan=lit, n_seeds=4, max_steps=MAX_STEPS,
            require_halt=False, latency=LatencySpec(ops=3),
        )
        # decode per seed: op 0 (before the kill) and op 2 (after the
        # restart) complete; op 1 (client down) is dropped at dispatch
        import jax

        from madsim_tpu.engine import make_init, make_run_while

        spec3 = LatencySpec(ops=3)
        seeds = np.arange(4, dtype=np.uint64)
        init = make_init(WL, CFG, plan_slots=lit.slots, latency=spec3)
        run = jax.jit(make_run_while(WL, CFG, MAX_STEPS, latency=spec3))
        out = jax.block_until_ready(
            run(init(seeds, lit.compile_batch(seeds, wl=WL)))
        )
        inv = np.asarray(out.lat_inv)
        resp = np.asarray(out.lat_resp)
        assert (inv[:, 0] >= 0).all() and (resp[:, 0] >= 0).all()
        assert (inv[:, 1] < 0).all()  # arrived at a dead client
        assert (inv[:, 2] >= 0).all() and (resp[:, 2] >= 0).all()
        assert (r.lat_count == 2).all()


class TestLatencyIdentity:
    def test_tap_off_vs_on_identical(self, reports):
        r_off, r_sc, _r_de, _r_co = reports
        assert np.array_equal(r_off.traces, r_sc.traces)
        assert r_off.lat_hist is None and r_off.lat_count is None
        assert r_sc.lat_hist.shape == (N_SEEDS, SPEC.phases, N_LAT_BUCKETS)

    def test_identical_across_layouts_and_compact(self, reports):
        _r_off, r_sc, r_de, r_co = reports
        for other in (r_de, r_co):
            assert np.array_equal(r_sc.traces, other.traces)
            assert np.array_equal(r_sc.lat_hist, other.lat_hist)
            assert np.array_equal(r_sc.lat_count, other.lat_count)

    def test_checkpoint_roundtrip_format9(self, tmp_path):
        import jax

        seeds = np.arange(4, dtype=np.uint64)
        init = make_init(WL, CFG, plan_slots=PLAN.slots, latency=SPEC)
        run = jax.jit(make_run(WL, CFG, 250, latency=SPEC))
        mid = run(init(seeds, PLAN.compile_batch(seeds, wl=WL)))
        path = str(tmp_path / "lat.ckpt")
        save_checkpoint(path, mid, CFG)
        resumed = run(load_checkpoint(path, CFG))
        straight = run(mid)
        assert np.array_equal(
            np.asarray(resumed.trace), np.asarray(straight.trace)
        )
        for f in ("lat_inv", "lat_resp", "lat_hist", "lat_count"):
            assert np.array_equal(
                np.asarray(getattr(resumed, f)),
                np.asarray(getattr(straight, f)),
            ), f


class TestSketch:
    def _exact(self, lat_state):
        inv = np.asarray(lat_state.lat_inv)
        resp = np.asarray(lat_state.lat_resp)
        done = (inv >= 0) & (resp >= 0)
        return (resp - inv)[done]

    def test_sketch_equals_exact_bucketing(self, reports, lat_state):
        """The merged fleet sketch IS the histogram of the concatenated
        per-op latencies — exact mergeability, the t-digest property
        the fixed ladder buys outright."""
        _r_off, r_sc, _r_de, _r_co = reports
        lats = self._exact(lat_state)
        assert lats.size > 30, "army produced too few completed ops"
        assert lats.min() > 0
        merged = r_sc.lat_hist.sum(axis=(0, 1))
        exact = np.bincount(lat_bucket(lats), minlength=N_LAT_BUCKETS)
        assert np.array_equal(merged, exact)
        assert merged.sum() == int(r_sc.lat_count.sum())

    def test_merge_matches_concatenation(self, reports):
        from madsim_tpu.parallel import merge_latency

        _r_off, r_sc, _r_de, _r_co = reports
        h = r_sc.lat_hist
        whole = merge_latency(h)
        halves = merge_latency(h[: N_SEEDS // 2]) + merge_latency(
            h[N_SEEDS // 2:]
        )
        assert np.array_equal(whole, halves)
        fl = obs.latency_reduce(h, r_sc.lat_count, phase_ns=SPEC.phase_ns)
        assert np.array_equal(fl.hist, whole)
        assert fl.completed == int(r_sc.lat_count.sum())
        assert "p99" in fl.format()

    def test_quantiles_within_one_bucket_of_exact(self, reports, lat_state):
        _r_off, r_sc, _r_de, _r_co = reports
        lats = self._exact(lat_state)
        merged = r_sc.lat_hist.sum(axis=(0, 1))
        for q in (0.5, 0.9, 0.99):
            sk = int(obs.hist_quantile_bucket(merged, q))
            exact_q = float(np.quantile(lats, q))
            assert abs(sk - int(lat_bucket(exact_q))) <= 1, (q, sk, exact_q)

    def test_fleet_latency_device_resident(self, reports):
        """The tail-only sweep returns the same totals as reducing the
        search report's columns — without a SearchReport in between."""
        _r_off, r_sc, _r_de, _r_co = reports
        fl = obs.fleet_latency(
            WL, CFG, SPEC, n_seeds=N_SEEDS, max_steps=MAX_STEPS, plan=PLAN,
        )
        ref = obs.latency_reduce(
            r_sc.lat_hist, r_sc.lat_count, phase_ns=SPEC.phase_ns
        )
        assert np.array_equal(fl.hist, ref.hist)
        assert fl.quantile(0.99) >= fl.quantile(0.5) > 0


class TestSlo:
    def test_clean_run_passes_generous_bound(self, reports):
        _r_off, r_sc, _r_de, _r_co = reports
        inv = check.slo_bounded(10_000_000_000, min_ops=1)
        ok = inv({"lat_hist": r_sc.lat_hist})
        assert ok.all()

    def test_provable_breach_flags_at_bucket_resolution(self):
        from madsim_tpu.engine import lat_bucket_hi, lat_bucket_lo

        h = np.zeros((2, 1, N_LAT_BUCKETS), np.int64)
        h[0, 0, 40] = 100  # every op lands in bucket 40
        h[1, 0, 10] = 100
        lo = int(lat_bucket_lo(40))
        # bound below the bucket: provably breached -> flagged
        assert np.array_equal(
            check.slo_breaches(h, lo - 1, min_ops=10), [True, False]
        )
        # bound AT the bucket's lower edge: not provable -> clean
        # (under-flag, never false-flag)
        assert not check.slo_breaches(h, lo, min_ops=10).any()
        # bound above: clean
        assert not check.slo_breaches(
            h, int(lat_bucket_hi(40)), min_ops=10
        ).any()
        # the min_ops floor keeps thin windows unjudged
        assert not check.slo_breaches(h, lo - 1, min_ops=101).any()

    def test_requires_latency_columns(self):
        with pytest.raises(ValueError, match="LatencySpec"):
            check.slo_bounded(1)( {"lat_hist": np.zeros((2, 0, 0))} )


class TestEmitTime:
    @pytest.fixture(scope="class")
    def ring_report(self):
        return search_seeds(
            WL, CFG, _ONES, layout="scatter", latency=SPEC,
            timeline_cap=2048, **_KW,
        )

    def test_emit_at_or_before_dispatch_and_refold_exact(self, ring_report):
        r = ring_report
        assert not r.tl_dropped.any()
        events = obs.decode_timeline(r.timeline, WL, 0)
        assert events, "empty timeline"
        assert all(e.emit_ns >= 0 for e in events)
        assert all(e.emit_ns <= e.time_ns for e in events)
        # a delivered message's emit time is some earlier dispatch of
        # the SENDER — the true send instant
        msgs = [e for e in events if e.src >= 0]
        assert msgs, "no messages captured"
        times_at = {}
        for e in events:
            times_at.setdefault(e.node, set()).add(e.time_ns)
        anchored = sum(
            1 for m in msgs if m.emit_ns in times_at.get(m.src, ())
        )
        assert anchored == len(msgs)
        # the sidecar never touches the certified trace
        assert obs.refold_timeline(events, WL) == int(r.traces[0])

    def test_perfetto_anchors_flows_at_emit(self, ring_report):
        events = obs.decode_timeline(ring_report.timeline, WL, 0)
        doc = obs.to_perfetto(events, WL, seed=0)
        rows = doc["traceEvents"]
        dispatch = [e for e in rows if e.get("cat") == "dispatch"]
        assert len(dispatch) == len(events)
        starts = [e for e in rows if e["ph"] == "s"]
        assert starts, "no flow arrows"
        emit_us = {}
        for e in events:
            if e.src >= 0:
                emit_us.setdefault(e.src, set()).add(e.emit_ns / 1e3)
        for s in starts:
            assert s["ts"] in emit_us[s["pid"]]


class TestExplain:
    def test_explain_narrates_tail_percentiles(self):
        text = obs.explain(
            WL, CFG, seed=1, plan=PLAN, max_steps=MAX_STEPS,
            timeline_cap=2048, latency=SPEC,
            invariant=check.slo_bounded(10_000_000_000, min_ops=1),
        )
        assert "--- latency:" in text
        assert "p99<=" in text
        assert "slowest completed:" in text
        assert "invariant HOLDS" in text


@pytest.mark.slow
class TestSloHunt:
    def test_guided_hunt_finds_shrinks_and_replays_breach(self):
        """The acceptance loop at test scale: a gray-failure space over
        the army, an SLO invariant, the guided campaign finds a breach,
        ddmin shrinks it, the shrunk literal replays to the identical
        violation + trace (the soak runs this at 2k-seed scale with a
        uniform-baseline comparison)."""
        from madsim_tpu import explore
        from madsim_tpu.chaos import shrink_plan

        wl = KV.make_kvchaos(writes=12, n_replicas=2, chaos=False, army=True)
        army = KV.client_army(
            n_ops=N_OPS, t_min_ns=5_000_000, t_max_ns=280_000_000,
            n_replicas=2,
        )
        space = FaultPlan(
            (army, GrayFailure(
                targets=(0, 3), n_links=2, mult_min=2, mult_max=64,
                dur_min_ns=150_000_000, dur_max_ns=400_000_000,
            )),
            name="slo-hunt",
        )
        slo = check.slo_bounded(60_000_000, q=0.99, min_ops=8)
        rep = explore.run(
            wl, CFG, space, invariant=slo, generations=4, batch=48,
            root_seed=11, max_steps=MAX_STEPS, cov_words=32,
            latency=SPEC,
        )
        assert rep.violations, "guided hunt found no SLO breach"
        entry = rep.violations[0]
        res = shrink_plan(
            wl, CFG, entry.seed, entry.plan, invariant=slo,
            max_steps=MAX_STEPS, latency=SPEC,
        )
        assert len(res.events) <= entry.plan.slots
        replay = explore.replay_entry(
            wl, CFG, dataclasses.replace(entry, plan=res.plan),
            invariant=slo, max_steps=MAX_STEPS, latency=SPEC,
        )
        assert int(replay.traces[0]) == res.trace
        assert not replay.ok[0], "shrunk plan no longer breaches"
