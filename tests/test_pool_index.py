"""Readiness-partitioned event pool (ISSUE 13): identity + knob pins.

The tile index is a pure LOWERING: pop via per-tile minima + the one
winning tile, free-slot search via per-tile free counts, summaries
carried as derived-by-construction columns. Everything observable —
traces, pools, histories, latency sketches, overflow counts — must be
bit-identical with the index on or off, across both write lowerings
(element stores / within-tile select chains), under time32, under
chaos + client-army plans, and through checkpoint save/restore (where
the summaries are REBUILT, never read from the file). The knob tests
pin the documented resolution rules (rank_place_max_pool default/env/
argument, pool_index auto thresholds) so a silent default change
fails here, not in a bench artifact.
"""

import dataclasses

import numpy as np
import pytest

import jax

from madsim_tpu.chaos import CrashStorm, FaultPlan, GrayFailure
from madsim_tpu.engine import (
    POOL_INDEX_STATE_FIELDS,
    EngineConfig,
    LatencySpec,
    Workload,
    build_pool_index,
    load_checkpoint,
    make_init,
    make_run,
    make_run_compacted,
    make_run_while,
    pool_tile,
    resolve_rank_place_max_pool,
    save_checkpoint,
)
from madsim_tpu.engine.core import (
    _POOL_INDEX_MIN_POOL,
    _RANK_PLACE_MAX_POOL,
    _resolve_pool_index,
    make_step,
)
from madsim_tpu.models import make_raft, make_raftlog
from madsim_tpu.models import raftlog as rl_mod

CFG = EngineConfig(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
SEEDS = np.arange(48, dtype=np.uint64)
N_STEPS = 260

# raftlog + army + chaos: extended kinds, client rows, history records
# and latency markers all flow through the indexed pop and placement
_ARMY_PLAN = FaultPlan((
    rl_mod.client_army(n_ops=10, t_min_ns=5_000_000, t_max_ns=400_000_000),
    CrashStorm(targets=tuple(range(5)), n=1, t_min_ns=50_000_000,
               t_max_ns=200_000_000, down_min_ns=20_000_000,
               down_max_ns=80_000_000),
    GrayFailure(targets=tuple(range(5)), n_links=1, mult_min=4, mult_max=8,
                t_min_ns=30_000_000, t_max_ns=150_000_000,
                dur_min_ns=50_000_000, dur_max_ns=150_000_000),
))
_LAT = LatencySpec(ops=10, phases=3)


def _fields(st, skip=POOL_INDEX_STATE_FIELDS):
    return {
        f.name: np.asarray(getattr(st, f.name))
        for f in dataclasses.fields(st)
        if f.name not in skip
    }


def _assert_state_equal(a, b, what=""):
    fa, fb = _fields(a), _fields(b)
    for name in fa:
        assert fa[name].shape == fb[name].shape, (what, name)
        assert np.array_equal(fa[name], fb[name]), (
            f"{what}: field {name!r} diverged between indexed and flat"
        )


def _assert_summaries_consistent(st, cfg):
    """The carried summaries equal a from-scratch rebuild (tile_min
    compared only on nonempty tiles — empty minima are stale by
    contract, the invalid-slot rule)."""
    tm, tc = build_pool_index(st.ev_time, st.ev_valid, pool_tile(cfg.pool_size))
    tc, tm = np.asarray(tc), np.asarray(tm)
    assert np.array_equal(tc, np.asarray(st.tile_cnt))
    mask = tc > 0
    assert np.array_equal(tm[mask], np.asarray(st.tile_min)[mask])


def _run_pair(wl, cfg, n_steps, seeds, plan=None, lat=None, **kw):
    slots = plan.slots if plan is not None else 0
    rows = plan.compile_batch(seeds, wl=wl) if plan is not None else None

    def one(pool_index, **extra):
        init = make_init(wl, cfg, plan_slots=slots, latency=lat,
                         pool_index=pool_index,
                         time32=extra.get("time32"))
        st0 = init(seeds, rows) if rows is not None else init(seeds)
        run = jax.jit(make_run(
            wl, cfg, n_steps, layout="scatter", latency=lat,
            pool_index=pool_index, **kw, **extra,
        ))
        return jax.block_until_ready(run(st0))

    return one


class TestIndexIdentity:
    def test_army_chaos_indexed_vs_flat_both_write_lowerings(self):
        wl = make_raftlog(record=True, army=True)
        cfg = EngineConfig(pool_size=96, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        one = _run_pair(wl, cfg, N_STEPS, SEEDS, plan=_ARMY_PLAN, lat=_LAT)
        flat = one(False)
        store = one(True, placement="scatter")
        chain = one(True, placement="rank")
        _assert_state_equal(flat, store, "element-store placement")
        _assert_state_equal(flat, chain, "within-tile select chains")
        _assert_summaries_consistent(store, cfg)
        _assert_summaries_consistent(chain, cfg)
        # the scenario actually completed client ops (the markers rode
        # the indexed placement, not a dead path)
        assert int(np.asarray(flat.lat_count).sum()) > 0

    def test_overflow_identity_under_pressure(self):
        # a pool too small for raft's traffic: drops must be counted
        # identically — the free-search rank math and flatnonzero agree
        # exactly at the boundary, not just in the spacious case
        wl = make_raft()
        cfg = EngineConfig(pool_size=16, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        one = _run_pair(wl, cfg, 200, SEEDS)
        flat, idx = one(False), one(True)
        assert int(np.asarray(flat.overflow).sum()) > 0
        _assert_state_equal(flat, idx, "overflow pressure")

    def test_time32_indexed_vs_flat(self):
        wl = make_raft()
        one = _run_pair(wl, CFG, 200, SEEDS)
        flat = one(False, time32=True)
        idx = one(True, time32=True)
        _assert_state_equal(flat, idx, "time32")
        _assert_summaries_consistent(idx, CFG)

    def test_run_while_and_compacted_indexed(self):
        wl = make_raft(record=True)
        cfg = EngineConfig(pool_size=40, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        init_f = make_init(wl, cfg, pool_index=False)
        init_i = make_init(wl, cfg, pool_index=True)
        ref = jax.block_until_ready(jax.jit(make_run_while(
            wl, cfg, 400, layout="scatter", pool_index=False
        ))(init_f(SEEDS)))
        got = jax.block_until_ready(jax.jit(make_run_while(
            wl, cfg, 400, layout="scatter", pool_index=True
        ))(init_i(SEEDS)))
        _assert_state_equal(ref, got, "run_while")
        out = make_run_compacted(
            wl, cfg, 400, layout="scatter", pool_index=True, min_size=8
        )(init_i(SEEDS))
        for name in ("now", "trace", "halted", "overflow", "node_state",
                     "hist_count", "hist_word"):
            assert np.array_equal(
                np.asarray(getattr(ref, name)), getattr(out, name)
            ), f"compacted {name} diverged"


class TestIndexEdgeCases:
    def test_time32_empty_tile_sentinel_decay(self):
        # regression (found in review): under time32 the per-step
        # rebase decays EVERY carried tile_min, including the +inf
        # sentinel of a long-empty tile; an insert burst spilling into
        # that tile after >2.1 sim-seconds used to fold min() against
        # the decayed sentinel, pinning the tile's minimum low and
        # silently popping the wrong event. The insert fold now masks
        # empty tiles back to the sentinel first.
        def handler(ctx):
            em = ctx.emits()
            count = ctx.state[0]
            em.after(100_000_000, 10, 0)  # 100 ms timer chain forever
            for j in range(9):  # at dispatch 25 (sim ~2.5 s), burst-
                # fill tile 0 so placement spills into the empty tile 1
                em.after(150_000_000 + j, 10, 0, when=count == 25)
            return ctx.state.at[0].set(count + 1), em.build()

        wl = Workload(name="sentinel-decay", n_nodes=1, state_width=1,
                      handlers=(handler,), max_emits=10,
                      delay_bound_ns=200_000_000)
        cfg = EngineConfig(pool_size=16, lat_min_ns=1_000_000,
                           lat_max_ns=2_000_000,
                           clog_backoff_max_ns=1_000_000_000)
        seeds = np.arange(4, dtype=np.uint64)
        outs = {}
        for pi in (False, True):
            st = make_init(wl, cfg, time32=True, pool_index=pi)(seeds)
            outs[pi] = jax.block_until_ready(jax.jit(make_run(
                wl, cfg, 60, layout="scatter", time32=True, pool_index=pi
            ))(st))
        _assert_state_equal(outs[False], outs[True], "sentinel decay")

    def test_dense_step_over_indexed_state(self, monkeypatch):
        # the mixed-resolution case the auto rule can produce on CPU
        # (layout-blind init auto-indexes a big pool, a forced dense
        # run has no index): the off-step must consume the state,
        # match the flat trajectory AND keep the carried summaries
        # exact (index-preserving rebuild), so a later indexed resume
        # can trust them
        monkeypatch.delenv("MADSIM_POOL_INDEX_MIN_POOL", raising=False)
        wl = make_raft()
        cfg = EngineConfig(pool_size=2048, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        seeds = np.arange(8, dtype=np.uint64)
        st = make_init(wl, cfg)(seeds)  # auto: indexed (CPU, pool 2048)
        assert st.tile_cnt.shape[1] == 2048 // pool_tile(2048)
        dense_out = jax.block_until_ready(jax.jit(make_run(
            wl, cfg, 150, layout="dense"
        ))(st))
        flat_out = jax.block_until_ready(jax.jit(make_run(
            wl, cfg, 150, layout="scatter", pool_index=False
        ))(make_init(wl, cfg, pool_index=False)(seeds)))
        _assert_state_equal(flat_out, dense_out, "dense over indexed state")
        _assert_summaries_consistent(dense_out, cfg)


class TestColdSplit:
    # tier-1 budget: three full army runs through the heaviest model;
    # the cold-split identity also holds under the stepident matrix
    # (slow) and the profile sweep, and tier-1 keeps the validation
    # guard plus the indexed-vs-flat identity pins above.
    @pytest.mark.slow
    def test_cold_split_bit_identical(self):
        wl = make_raftlog(record=True, army=True)
        cfg = EngineConfig(pool_size=96, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        one = _run_pair(wl, cfg, N_STEPS, SEEDS, plan=_ARMY_PLAN, lat=_LAT)
        hot = one(False)
        cold = one(False, cold_split=True)
        both = one(True, cold_split=True)
        _assert_state_equal(hot, cold, "cold_split")
        _assert_state_equal(hot, both, "cold_split + pool_index")
        assert int(np.asarray(hot.lat_count).sum()) > 0

    def test_cold_split_validation(self):
        wl = make_raftlog(army=True)
        with pytest.raises(ValueError, match="cold_split needs"):
            make_run(wl, CFG, 10, cold_split=True)
        with pytest.raises(ValueError, match="incompatible with coverage"):
            make_run(wl, CFG, 10, latency=_LAT, cov_words=8, cold_split=True)


class TestCheckpoint:
    def _run_some(self, wl, cfg, n, state, pool_index):
        return jax.block_until_ready(jax.jit(make_run(
            wl, cfg, n, layout="scatter", pool_index=pool_index
        ))(state))

    def test_roundtrip_rebuilds_summaries(self, tmp_path):
        wl = make_raft(record=True)
        cfg = EngineConfig(pool_size=40, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        mid = self._run_some(
            wl, cfg, 150, make_init(wl, cfg, pool_index=True)(SEEDS), True
        )
        p = str(tmp_path / "idx.npz")
        save_checkpoint(p, mid, cfg)
        # the file carries NO summary entries — they are not format
        with np.load(p) as data:
            for f in POOL_INDEX_STATE_FIELDS:
                assert f not in data.files
        back = load_checkpoint(p, cfg, pool_index=True)
        # rebuilt summaries equal a from-scratch build over the loaded
        # pool (count exactly; minima on nonempty tiles)
        tm, tc = build_pool_index(
            back.ev_time, back.ev_valid, pool_tile(cfg.pool_size)
        )
        assert np.array_equal(np.asarray(tc), np.asarray(back.tile_cnt))
        mask = np.asarray(tc) > 0
        assert np.array_equal(
            np.asarray(tm)[mask], np.asarray(back.tile_min)[mask]
        )
        # resuming from the restore equals the uninterrupted run
        full = self._run_some(wl, cfg, 300,
                              make_init(wl, cfg, pool_index=True)(SEEDS), True)
        resumed = self._run_some(wl, cfg, 150, back, True)
        _assert_state_equal(full, resumed, "checkpoint resume")

    def test_flat_checkpoint_loads_into_indexed_run(self, tmp_path):
        # "old checkpoints load unchanged": a state saved by an
        # index-off run (byte-identical to the pre-index format) feeds
        # an indexed resume, and the trajectory matches the flat one
        wl = make_raft()
        cfg = EngineConfig(pool_size=40, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        mid = self._run_some(
            wl, cfg, 150, make_init(wl, cfg, pool_index=False)(SEEDS), False
        )
        p = str(tmp_path / "flat.npz")
        save_checkpoint(p, mid, cfg)
        back = load_checkpoint(p, cfg, pool_index=True)
        assert back.tile_cnt.shape == (
            len(SEEDS), cfg.pool_size // pool_tile(cfg.pool_size)
        )
        resumed_idx = self._run_some(wl, cfg, 150, back, True)
        resumed_flat = self._run_some(
            wl, cfg, 150, load_checkpoint(p, cfg, pool_index=False), False
        )
        _assert_state_equal(resumed_flat, resumed_idx, "cross-format resume")


class TestKnobs:
    def test_rank_place_max_pool_resolution(self, monkeypatch):
        monkeypatch.delenv("MADSIM_RANK_PLACE_MAX_POOL", raising=False)
        assert resolve_rank_place_max_pool() == _RANK_PLACE_MAX_POOL == 512
        monkeypatch.setenv("MADSIM_RANK_PLACE_MAX_POOL", "64")
        assert resolve_rank_place_max_pool() == 64
        # the explicit argument beats the env override
        assert resolve_rank_place_max_pool(2048) == 2048
        with pytest.raises(ValueError):
            resolve_rank_place_max_pool(-1)
        # env typos name the variable; negatives are rejected like the
        # explicit argument (no silent nonsense from a deployment typo)
        monkeypatch.setenv("MADSIM_RANK_PLACE_MAX_POOL", "abc")
        with pytest.raises(ValueError, match="MADSIM_RANK_PLACE_MAX_POOL"):
            resolve_rank_place_max_pool()
        monkeypatch.setenv("MADSIM_RANK_PLACE_MAX_POOL", "-5")
        with pytest.raises(ValueError, match="MADSIM_RANK_PLACE_MAX_POOL"):
            resolve_rank_place_max_pool()

    def test_pool_tile_divisors(self):
        assert pool_tile(2048) == 64
        assert pool_tile(8192) == 64
        assert pool_tile(96) == 32
        assert pool_tile(40) == 8
        assert pool_tile(72) == 8
        assert pool_tile(7) == 0  # no candidate divides it
        assert pool_tile(64) == 32  # needs >= 2 tiles

    def test_pool_index_auto_rule(self, monkeypatch):
        monkeypatch.delenv("MADSIM_POOL_INDEX_MIN_POOL", raising=False)
        # CPU backend (the test env): auto on only past the threshold
        assert not _resolve_pool_index(EngineConfig(pool_size=512), None)
        assert not _resolve_pool_index(
            EngineConfig(pool_size=_POOL_INDEX_MIN_POOL), None
        )
        assert _resolve_pool_index(EngineConfig(pool_size=2048), None)
        # dense layout never auto-engages, and explicit True rejects it
        assert not _resolve_pool_index(
            EngineConfig(pool_size=2048), None, dense=True
        )
        with pytest.raises(ValueError, match="dense"):
            _resolve_pool_index(EngineConfig(pool_size=2048), True, dense=True)
        with pytest.raises(ValueError, match="no tile divisor"):
            _resolve_pool_index(EngineConfig(pool_size=2049), True)
        monkeypatch.setenv("MADSIM_POOL_INDEX_MIN_POOL", "256")
        assert _resolve_pool_index(EngineConfig(pool_size=512), None)

    def test_default_placement_under_index_is_store(self):
        # the measured CPU default (SCALING.md round 9): under the
        # index, placement writes default to element stores whatever
        # the pool size; without it, the PR-8 crossover rule holds
        cfg_small = EngineConfig(pool_size=64)
        wl = make_raft()
        # builds must succeed; the resolution itself is pinned via the
        # error path (placement="bogus" names the resolved set)
        make_step(wl, cfg_small, layout="scatter", pool_index=True)
        with pytest.raises(ValueError, match="unknown placement"):
            make_step(wl, cfg_small, layout="scatter", placement="bogus")

    def test_army_pool_sizing_is_tile_aligned(self):
        wl = make_raftlog(army=True)
        plan = FaultPlan((
            rl_mod.client_army(n_ops=1000),
            CrashStorm(targets=(0,), n=1),
        ))
        size = plan.min_pool_size(wl)
        assert size >= wl.n_nodes + plan.slots + 16
        assert size % 64 == 0 and pool_tile(size) == 64
        raw = plan.min_pool_size(wl, headroom=0, tile_align=False)
        assert raw == wl.n_nodes + plan.slots

    def test_mismatched_state_raises(self):
        wl = make_raft()
        cfg = EngineConfig(pool_size=40, loss_p=0.02,
                           clog_backoff_max_ns=2_000_000_000)
        st = make_init(wl, cfg, pool_index=False)(SEEDS)
        step = make_step(wl, cfg, layout="scatter", pool_index=True)
        with pytest.raises(TypeError, match="pool-index tiles"):
            jax.vmap(step)(st)


@pytest.mark.slow
class TestExploreDevicePin:
    """The explore-device campaign identity pin with the index on: the
    whole device-resident loop (mutation, sweep, admission) runs the
    indexed step and produces the bit-identical campaign."""

    def test_device_campaign_index_on_off(self):
        from madsim_tpu import explore
        from madsim_tpu.chaos import GrayFailure, PauseStorm

        nodes = (0, 1, 2, 3, 4)
        cfg = EngineConfig(pool_size=64, loss_p=0.02)
        plan = FaultPlan((
            PauseStorm(targets=nodes, n=1, t_min_ns=20_000_000,
                       t_max_ns=300_000_000, down_min_ns=50_000_000,
                       down_max_ns=200_000_000),
            GrayFailure(targets=nodes, n_links=1),
        ), name="pool-index-pin")

        def inv(view):
            return view["halted"]

        kw = dict(generations=2, batch=16, root_seed=7, max_steps=500,
                  cov_words=16, invariant=inv)
        off = explore.run_device(make_raft(), cfg, plan, pool_index=False, **kw)
        on = explore.run_device(make_raft(), cfg, plan, pool_index=True, **kw)

        def fp(rep):
            return (
                [(e.id, e.generation, e.parent, e.seed, e.plan.hash(),
                  e.trace, e.new_bits, e.violating) for e in rep.corpus],
                rep.cov_map.tolist(),
                [(e.seed, e.trace) for e in rep.violations],
                rep.curve,
            )

        assert fp(off) == fp(on)
