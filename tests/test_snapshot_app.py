"""The application-level Lai-Yang snapshot (examples/snapshot_app.py):
the same algorithm the engine family certifies over 65k schedules,
written and checked the way a user would on the single-seed runtime."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

from snapshot_app import BALANCE, N_NODES, run_snapshot


@pytest.mark.parametrize("seed", [1, 2, 3, 17, 99])
def test_conservation_over_the_cut(seed):
    out = run_snapshot(seed)
    assert all(c == 1 for c in out["colors"].values()), "every branch red"
    assert all(r is not None for r in out["recorded"].values())
    cut = sum(out["recorded"].values()) + sum(out["chan_in"].values())
    assert cut == N_NODES * BALANCE, out
    assert sum(out["balances"].values()) == N_NODES * BALANCE


def test_deterministic_per_seed():
    assert run_snapshot(7) == run_snapshot(7)
    assert run_snapshot(7) != run_snapshot(8)


def test_some_seed_captures_channel_state():
    """The cut is non-trivial: across seeds, some snapshot must catch
    money in flight (otherwise the channel-state machinery is dead
    code and conservation would hold trivially)."""
    assert any(
        sum(run_snapshot(s)["chan_in"].values()) > 0 for s in range(1, 12)
    )
