"""First-class client retries (ISSUE 20): timeout/backoff RetryPolicy.

The contract under test, clause by clause:

* **Derived-state discipline, off-policy.** ``retry=None`` is the
  pre-retry engine: zero-size ``rt_*`` columns, untouched metric slots,
  and bit-identical traces across the scatter/dense lowerings, the
  time32 representation, the readiness-indexed pool and the compacted
  runner. A policied plan compiles the SAME pool rows as the unpolicied
  one (attempt-0 tokens are plain op ids) — the policy changes the
  engine build, never the compiled plan.
* **Deterministic schedule.** The backoff ladder is a host-side
  constant table; re-send jitter comes from ``(seed, step)`` threefry
  draws on the PURPOSE_RETRY lane — the same seed replays the same
  attempt schedule down to every SimState bit, and retried runs stay
  bit-identical across lowerings.
* **Books.** MET_RETRY counts delivered re-sends, MET_RETRY_GIVEUP
  abandoned ops; under total response starvation the counts are exact:
  ``(max_attempts - 1) * n_ops`` re-sends, ``n_ops`` give-ups, zero
  completed latency samples.
* **Checkpoints.** Format 11 carries the ``rt_*`` columns (armed
  deadlines are core state): a retried run snapshots and resumes
  bit-identically, and mismatched retry axes are refused with the
  designed error in both directions.
* **Attempt-aware checking.** ``check.exactly_once`` and
  ``check.collapse_retries`` agree verdict-for-verdict (and bit-for-bit)
  between the numpy oracles and the jnp device kernels, on hand-built
  oracle tables covering the OK / FAIL / PENDING response shapes and on
  real clean/mutant batches.
* **The planted mutant.** ``shardkv(bug="noidem")`` applies every
  delivered attempt; under a retry policy it is INVISIBLE to the
  final-state shard_coverage checker and caught only by exactly_once —
  found by the guided hunt, ddmin-shrunk under the same policy, and
  replayed to the identical violation + trace.

tools/retry_soak.py runs the same certificates at evidence scale
(RETRY_r14.txt).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from madsim_tpu import check
from madsim_tpu.chaos import (
    FaultPlan,
    GrayFailure,
    Partition,
    RetryPolicy,
    shrink_plan,
)
from madsim_tpu.check import BatchHistory, OK_FAIL, OK_OK, OK_PENDING
from madsim_tpu.check import device as dc
from madsim_tpu.engine import (
    MET_RETRY,
    MET_RETRY_GIVEUP,
    N_METRICS,
    RETRY_STATE_FIELDS,
    EngineConfig,
    LatencySpec,
    RetrySpec,
    load_checkpoint,
    make_init,
    make_run,
    make_run_while,
    retry_token,
    retry_token_attempt,
    retry_token_op,
    save_checkpoint,
    search_seeds,
)
from madsim_tpu.engine.compact import make_run_compacted
from madsim_tpu.engine.core import _retry_backoff_tables, time32_eligible
from madsim_tpu.models import kvchaos as KV
from madsim_tpu.models import shardkv as SK

# the pinned retry-amplification shape: 2-replica kvchaos army under a
# gray-failure slow link, 50 ms response deadline
N_OPS = 16
POLICY = RetryPolicy(timeout_ns=50_000_000, max_attempts=3,
                     backoff_base_ns=10_000_000, backoff_mult=2.0,
                     jitter=0.5)
GRAY = GrayFailure(targets=(0, 3), n_links=1, mult_min=6, mult_max=12)
CFG = EngineConfig(pool_size=64, time_limit_ns=450_000_000,
                   clog_backoff_max_ns=2_000_000_000)
SPEC = LatencySpec(ops=N_OPS, phases=3, phase_ns=1 << 27)
STEPS = 1500


def _wl():
    return KV.make_kvchaos(writes=12, n_replicas=2, chaos=False, army=True)


def _plan(retry):
    return FaultPlan(
        (KV.client_army(n_ops=N_OPS, t_min_ns=5_000_000,
                        t_max_ns=280_000_000, n_replicas=2, retry=retry),
         GRAY),
        name="retry-pin",
    )


def _run(wl, plan, seeds, retry, *, layout=None, time32=None,
         pool_index=None, compact=False, steps=STEPS):
    kw = dict(latency=SPEC, metrics=True, retry=retry)
    init = make_init(wl, CFG, plan_slots=plan.slots, time32=time32,
                     pool_index=pool_index, **kw)
    st0 = init(seeds, plan.compile_batch(seeds, wl=wl))
    if compact:
        run = make_run_compacted(wl, CFG, steps, layout=layout,
                                 time32=time32, pool_index=pool_index,
                                 min_size=8, **kw)
        return run(st0)
    run = jax.jit(make_run_while(wl, CFG, steps, layout=layout,
                                 time32=time32, pool_index=pool_index,
                                 **kw))
    return jax.block_until_ready(run(st0))


# ------------------------------------------------------------- identity
class TestOffIdentity:
    def test_retry_off_columns_are_zero_size(self):
        wl = _wl()
        plan = _plan(POLICY)
        seeds = np.arange(4, dtype=np.uint64)
        rows = plan.compile_batch(seeds, wl=wl)
        off = make_init(wl, CFG, plan_slots=plan.slots, latency=SPEC,
                        metrics=True)(seeds, rows)
        on = make_init(wl, CFG, plan_slots=plan.slots, latency=SPEC,
                       metrics=True,
                       retry=plan.retry_spec())(seeds, rows)
        for f in RETRY_STATE_FIELDS:
            assert np.asarray(getattr(off, f)).size == 0, f
            assert np.asarray(getattr(on, f)).shape == (4, N_OPS), f
        # the metric row grew the two retry slots for every build — the
        # schema-only change the step goldens digest around
        assert np.asarray(off.met).shape == (4, N_METRICS)
        assert N_METRICS == MET_RETRY_GIVEUP + 1

    def test_policy_changes_no_compiled_row(self):
        """The plan compiles identically with and without the policy:
        attempt-0 tokens ARE plain op ids, so the offered load is the
        same rows and the policy is purely an engine build flag."""
        seeds = np.arange(8, dtype=np.uint64)
        wl = _wl()
        r_on = _plan(POLICY).compile_batch(seeds, wl=wl)
        r_off = _plan(None).compile_batch(seeds, wl=wl)
        for f in ("time", "kind", "args", "valid", "node"):
            assert np.array_equal(np.asarray(getattr(r_on, f)),
                                  np.asarray(getattr(r_off, f))), f

    @pytest.mark.parametrize("axis", ["dense", "time32", "pool_index",
                                      "compact"])
    def test_retry_off_bit_identity_four_axes(self, axis):
        """With no policy the retry machinery compiles away on every
        lowering: trace/clock/books identical to the scatter baseline
        (the step-golden digests in test_stepident.py pin the same
        engine against its PRE-retry values)."""
        wl = _wl()
        plan = _plan(None)
        seeds = np.arange(6, dtype=np.uint64)
        base = _run(wl, plan, seeds, None, layout="scatter")
        kw = {
            "dense": dict(layout="dense"),
            "time32": dict(time32=True),
            "pool_index": dict(pool_index=True),
            "compact": dict(compact=True),
        }[axis]
        if axis == "time32":
            assert time32_eligible(wl, CFG)
        other = _run(wl, plan, seeds, None, **kw)
        for f in ("trace", "now", "step", "halted", "met", "lat_hist"):
            assert np.array_equal(np.asarray(getattr(base, f)),
                                  np.asarray(getattr(other, f))), (axis, f)
        assert np.asarray(base.met)[:, MET_RETRY:].sum() == 0

    @pytest.mark.parametrize("axis", ["dense", "time32", "pool_index",
                                      "compact"])
    def test_retry_on_bit_identity_four_axes(self, axis):
        """A retried trajectory is still one trajectory: the re-sent
        attempts land identically on every lowering. (The compacted
        runner banks RESULT_FIELDS only, so the rt_* books are compared
        on the full-state axes.)"""
        wl = _wl()
        plan = _plan(POLICY)
        rt = plan.retry_spec()
        seeds = np.arange(6, dtype=np.uint64)
        base = _run(wl, plan, seeds, rt, layout="scatter")
        assert np.asarray(base.met)[:, MET_RETRY].sum() > 0
        kw = {
            "dense": dict(layout="dense"),
            "time32": dict(time32=True),
            "pool_index": dict(pool_index=True),
            "compact": dict(compact=True),
        }[axis]
        other = _run(wl, plan, seeds, rt, **kw)
        fields = ["trace", "now", "step", "halted", "met", "lat_hist"]
        if axis != "compact":
            fields += list(RETRY_STATE_FIELDS)
        for f in fields:
            assert np.array_equal(np.asarray(getattr(base, f)),
                                  np.asarray(getattr(other, f))), (axis, f)


# ------------------------------------------------------------- schedule
class TestSchedule:
    def test_token_packing_roundtrip(self):
        for op in (0, 7, (1 << 26) - 1):
            for att in (0, 1, 15):
                tok = retry_token(op, att)
                assert retry_token_op(tok) == op
                assert retry_token_attempt(tok) == att
        assert retry_token(9, 0) == 9  # attempt-0 tokens are plain ids

    def test_backoff_table_pin(self):
        """The deterministic ladder: entry a = base * mult**(a-1) before
        delivering attempt a; the jitter table is the ladder scaled by
        the policy's jitter fraction."""
        rt = RetrySpec(kind=16, node=0, op_base=0, n_ops=4,
                       timeout_ns=1, max_attempts=4,
                       backoff_base_ns=10_000_000, backoff_mult=2.0,
                       jitter=0.5)
        boff, bjit = _retry_backoff_tables(rt)
        assert boff == (0, 10_000_000, 20_000_000, 40_000_000, 80_000_000)
        assert bjit == (0, 5_000_000, 10_000_000, 20_000_000, 40_000_000)

    def test_spec_validation(self):
        ok = dict(kind=16, node=0, op_base=0, n_ops=4, timeout_ns=1)
        RetrySpec(**ok)
        with pytest.raises(ValueError, match="max_attempts"):
            RetrySpec(**ok, max_attempts=16)
        with pytest.raises(ValueError, match="token op field"):
            RetrySpec(kind=16, node=0, op_base=(1 << 26) - 2, n_ops=4,
                      timeout_ns=1)
        with pytest.raises(ValueError, match="user kind"):
            RetrySpec(kind=2, node=0, op_base=0, n_ops=4, timeout_ns=1)
        with pytest.raises(ValueError, match="jitter"):
            RetrySpec(**ok, jitter=1.5)

    def test_same_seed_same_attempt_schedule(self):
        """Two independent builds of the same retried run agree on every
        SimState bit — the attempt schedule (deadlines, backoff draws,
        re-send times) is a pure function of the seed."""
        wl = _wl()
        plan = _plan(POLICY)
        rt = plan.retry_spec()
        seeds = np.arange(4, dtype=np.uint64)
        a = _run(wl, plan, seeds, rt)
        b = _run(_wl(), _plan(POLICY), seeds, rt)
        for f in dataclasses.fields(a):
            assert np.array_equal(
                np.asarray(getattr(a, f.name)),
                np.asarray(getattr(b, f.name)),
            ), f.name
        met = np.asarray(a.met)
        assert met[:, MET_RETRY].sum() > 0  # the schedule was exercised

    def test_retry_changes_the_trajectory(self):
        """The policy is core state, not an observability tap: armed
        deadline rows dispatch (delivering or folding as suppressed
        no-ops), so any seed that re-sent has a different trace from
        the fire-and-forget run."""
        wl = _wl()
        seeds = np.arange(4, dtype=np.uint64)
        on = _run(wl, _plan(POLICY), seeds, _plan(POLICY).retry_spec())
        off = _run(wl, _plan(None), seeds, None)
        retried = np.asarray(on.met)[:, MET_RETRY] > 0
        assert retried.any()
        diverged = np.asarray(on.trace) != np.asarray(off.trace)
        assert diverged[retried].all()


# ------------------------------------------------------------- give-ups
class TestGiveup:
    def test_starved_army_gives_up_exactly(self):
        """Client cut off from the primary for the whole horizon: every
        op delivers all max_attempts attempts then abandons — re-send
        and give-up books are exact, nothing completes."""
        wl = KV.make_kvchaos(writes=4, n_replicas=2, chaos=False,
                             army=True)
        pol = RetryPolicy(timeout_ns=20_000_000, max_attempts=3,
                          backoff_base_ns=5_000_000, backoff_mult=2.0)
        plan = FaultPlan(
            (KV.client_army(n_ops=6, t_min_ns=5_000_000,
                            t_max_ns=80_000_000, n_replicas=2, retry=pol),
             Partition(targets=(0, 3), t_min_ns=1, t_max_ns=2,
                       dur_min_ns=900_000_000, dur_max_ns=900_000_001)),
            name="starve",
        )
        cfg = EngineConfig(pool_size=80, time_limit_ns=700_000_000)
        rt = plan.retry_spec()
        seeds = np.arange(8, dtype=np.uint64)
        init = make_init(wl, cfg, plan_slots=plan.slots,
                         latency=LatencySpec(ops=6), metrics=True,
                         retry=rt)
        run = jax.jit(make_run_while(wl, cfg, 5000,
                                     latency=LatencySpec(ops=6),
                                     metrics=True, retry=rt))
        out = jax.block_until_ready(
            run(init(seeds, plan.compile_batch(seeds, wl=wl))))
        met = np.asarray(out.met)
        assert (met[:, MET_RETRY] == (pol.max_attempts - 1) * 6).all()
        assert (met[:, MET_RETRY_GIVEUP] == 6).all()
        assert np.asarray(out.rt_done).sum() == 0
        assert np.asarray(out.lat_hist).sum() == 0
        assert np.asarray(out.halted).all()


# ----------------------------------------------------------- checkpoint
class TestCheckpoint:
    def test_retry_roundtrip_resumes_identically(self, tmp_path):
        wl = _wl()
        plan = _plan(POLICY)
        rt = plan.retry_spec()
        seeds = np.arange(4, dtype=np.uint64)
        kw = dict(latency=SPEC, metrics=True, retry=rt)
        init = make_init(wl, CFG, plan_slots=plan.slots, **kw)
        run = jax.jit(make_run(wl, CFG, 300, **kw))
        mid = jax.block_until_ready(
            run(init(seeds, plan.compile_batch(seeds, wl=wl))))
        # armed deadlines must actually be in flight at the cut for the
        # roundtrip to prove anything
        assert np.asarray(mid.rt_deadline).max() > 0
        p = str(tmp_path / "retry.npz")
        save_checkpoint(p, mid, CFG)
        resumed = jax.block_until_ready(
            run(load_checkpoint(p, CFG, retry=rt)))
        straight = jax.block_until_ready(run(mid))
        for f in dataclasses.fields(straight):
            assert np.array_equal(
                np.asarray(getattr(straight, f.name)),
                np.asarray(getattr(resumed, f.name)),
            ), f.name

    def test_mismatched_axes_refused_both_directions(self, tmp_path):
        wl = _wl()
        plan = _plan(POLICY)
        rt = plan.retry_spec()
        seeds = np.arange(2, dtype=np.uint64)
        rows = plan.compile_batch(seeds, wl=wl)
        on = make_init(wl, CFG, plan_slots=plan.slots, latency=SPEC,
                       retry=rt)(seeds, rows)
        off = make_init(wl, CFG, plan_slots=plan.slots,
                        latency=SPEC)(seeds, rows)
        p_on = str(tmp_path / "on.npz")
        p_off = str(tmp_path / "off.npz")
        save_checkpoint(p_on, on, CFG)
        save_checkpoint(p_off, off, CFG)
        with pytest.raises(ValueError, match="no retry policy"):
            load_checkpoint(p_on, CFG)
        with pytest.raises(ValueError, match="retry.n_ops"):
            load_checkpoint(p_off, CFG, retry=rt)
        with pytest.raises(ValueError, match="retry columns"):
            load_checkpoint(
                p_on, CFG, retry=dataclasses.replace(rt, n_ops=8)
            )
        # the matching spec loads cleanly both ways
        assert np.asarray(load_checkpoint(p_on, CFG, retry=rt)
                          .rt_done).shape == (2, N_OPS)
        assert np.asarray(load_checkpoint(p_off, CFG).rt_done).size == 0


# -------------------------------------------------- exactly-once oracle
_AP = 7  # the apply op under test (any user op id works for the oracle)


def _hist(seeds_rows):
    """Hand-built BatchHistory: per-seed lists of (op, key, arg,
    client, ok) rows — the COL_* order of check/history.py."""
    s = len(seeds_rows)
    h = max(len(r) for r in seeds_rows)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    for i, rows in enumerate(seeds_rows):
        for j, r in enumerate(rows):
            word[i, j] = r
            t[i, j] = 10 * (j + 1)
    return BatchHistory(
        word=word, t=t,
        count=np.asarray([len(r) for r in seeds_rows], np.int32),
        drop=np.zeros(s, np.int32),
    )


# the oracle table: the three response shapes (OK / FAIL / PENDING)
# against the discriminating columns (client, key=op id)
_ORACLE = [
    # clean: one successful apply per (client, op id)
    ([(_AP, 1, 0, 0, OK_OK), (_AP, 2, 0, 0, OK_OK),
      (_AP, 1, 0, 1, OK_OK)], True),
    # duplicate success, same (client, op id): the violation
    ([(_AP, 1, 0, 0, OK_OK), (_AP, 2, 1, 0, OK_OK),
      (_AP, 1, 1, 0, OK_OK)], False),
    # FAIL response shape: a failed re-apply is not a double apply
    ([(_AP, 1, 0, 0, OK_OK), (_AP, 1, 1, 0, OK_FAIL)], True),
    # PENDING response shape: re-sent invokes are never counted
    ([(_AP, 1, 0, 0, OK_PENDING), (_AP, 1, 1, 0, OK_PENDING),
      (_AP, 1, 1, 0, OK_OK)], True),
    # same op id, different clients: two sessions may both apply
    ([(_AP, 1, 0, 0, OK_OK), (_AP, 1, 0, 1, OK_OK)], True),
    # other ops never counted, even duplicated
    ([(_AP + 1, 1, 0, 0, OK_OK), (_AP + 1, 1, 0, 0, OK_OK)], True),
]


class TestExactlyOnce:
    def test_oracle_table_numpy_equals_device(self):
        h = _hist([rows for rows, _ in _ORACLE])
        want = np.asarray([ok for _, ok in _ORACLE])
        got_np = check.exactly_once(h, _AP)
        assert np.array_equal(got_np, want)
        got_dev = np.asarray(dc.screen_ok(
            (dc.exactly_once(_AP),),
            jnp.asarray(h.word), jnp.asarray(h.t),
            jnp.asarray(h.count), jnp.asarray(h.drop),
        ))
        assert np.array_equal(got_dev, want)
        # the HistoryScreen host oracle is the numpy function itself
        assert np.array_equal(dc.exactly_once(_AP).host(h), got_np)

    def test_empty_history_is_clean(self):
        h = BatchHistory(word=np.zeros((3, 0, 5), np.int32),
                         t=np.zeros((3, 0), np.int64),
                         count=np.zeros(3, np.int32),
                         drop=np.zeros(3, np.int32))
        assert check.exactly_once(h, _AP).all()

    def test_real_batches_clean_and_mutant(self):
        """shardkv army under retries: the clean guard dedups every
        re-delivered attempt; noidem applies them all — and only
        exactly_once sees it (shard_coverage passes both ways)."""
        verdicts = {}
        for bug in (False, "noidem"):
            wl = SK.make_shardkv(record=True, chaos=False, army=True,
                                 bug=bug)
            pol = RetryPolicy(timeout_ns=8_000_000, max_attempts=3,
                              backoff_base_ns=4_000_000,
                              backoff_mult=2.0, jitter=0.25)
            plan = FaultPlan(
                (SK.client_army(n_ops=16, t_min_ns=5_000_000,
                                t_max_ns=280_000_000, retry=pol),
                 GrayFailure(targets=(0, 1), n_links=1, mult_min=8,
                             mult_max=16)),
                name="noidem-pin",
            )
            cfg = EngineConfig(pool_size=96, time_limit_ns=600_000_000)
            rt = plan.retry_spec()
            seeds = np.arange(8, dtype=np.uint64)
            init = make_init(wl, cfg, plan_slots=plan.slots,
                             latency=LatencySpec(ops=16), retry=rt)
            run = jax.jit(make_run_while(wl, cfg, 3000,
                                         latency=LatencySpec(ops=16),
                                         retry=rt))
            out = jax.block_until_ready(
                run(init(seeds, plan.compile_batch(seeds, wl=wl))))
            h = BatchHistory.from_state(out)
            v_np = check.exactly_once(h, SK.OP_ARMY_PUT)
            v_dev = np.asarray(dc.screen_ok(
                (dc.exactly_once(SK.OP_ARMY_PUT),),
                jnp.asarray(out.hist_word), jnp.asarray(out.hist_t),
                jnp.asarray(out.hist_count), jnp.asarray(out.hist_drop),
            ))
            assert np.array_equal(v_np, v_dev), bug
            # the final-state checker is blind to the double-applies
            assert np.asarray(check.shard_coverage(
                h, SK.OP_SHARD_OWN, SK.OP_SHARD_WRITE
            )).all(), bug
            verdicts[bug] = v_np
        assert verdicts[False].all()
        assert not verdicts["noidem"].all()


class TestCollapseRetries:
    def test_collapse_rule_pinned(self):
        """An invoke collapses iff an earlier invoke of its (client,
        op, key) group has no group response between them; collapsed
        rows get COL_OP cleared, nothing else moves."""
        rows = [
            (_AP, 1, 0, 0, OK_PENDING),  # first attempt
            (_AP, 1, 1, 0, OK_PENDING),  # re-send, no response between
            (_AP, 1, 1, 0, OK_OK),       # the response
            (_AP, 1, 2, 0, OK_PENDING),  # fresh invoke AFTER the response
            (_AP, 2, 0, 0, OK_PENDING),  # different key: untouched
        ]
        h = _hist([rows])
        c = check.collapse_retries(h)
        assert c.word[0, :, 0].tolist() == [_AP, 0, _AP, _AP, _AP]
        # only COL_OP of the collapsed row changed
        assert np.array_equal(c.word[..., 1:], np.asarray(h.word)[..., 1:])
        assert np.array_equal(c.t, h.t)
        assert np.array_equal(c.count, h.count)

    def test_numpy_equals_device(self):
        h = _hist([rows for rows, _ in _ORACLE]
                  + [[(_AP, 1, a, 0, OK_PENDING) for a in range(4)]])
        c_np = check.collapse_retries(h)
        c_dev = np.asarray(dc.collapse_retries_cols(
            jnp.asarray(h.word), jnp.asarray(h.count)
        ))
        assert np.array_equal(np.asarray(c_np.word), c_dev)


# ------------------------------------------------------- search wiring
class TestSearchWiring:
    def test_search_seeds_derives_retry_from_plan(self):
        """``search_seeds(plan=...)`` arms the timers from the plan's
        own RetryPolicy with no further wiring — the report's books
        show re-sends."""
        wl = _wl()
        plan = _plan(POLICY)
        ones = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731
        r = search_seeds(
            wl, CFG, ones, n_seeds=4, max_steps=STEPS, plan=plan,
            latency=SPEC, metrics=True, require_halt=False,
        )
        assert np.asarray(r.met)[:, MET_RETRY].sum() > 0

    def test_two_policied_armies_refused(self):
        a = KV.client_army(n_ops=4, n_replicas=2, retry=POLICY)
        b = KV.client_army(n_ops=4, n_replicas=2, op_base=4,
                           retry=POLICY)
        plan = FaultPlan((a, b), name="double")
        with pytest.raises(ValueError, match="one retried op range"):
            plan.retry_spec()
        assert _plan(None).retry_spec() is None


# ------------------------------------------------- perfetto arrow labels
class TestPerfettoLabels:
    """Regression pin for the (op, attempt) arrow naming (ISSUE 20
    satellite: the Duplicate-class mis-anchors banked in CAUSAL_r13.txt
    are ambiguous re-sends — the label now disambiguates them)."""

    def _events(self, att):
        from madsim_tpu.engine.replay import ReplayEvent

        tok = retry_token(7, att)
        return [
            # the send: a dispatch at node 1 that emitted the message
            ReplayEvent(time_ns=1_000, kind=16, node=1, src=-1,
                        args=(0, 0), pay=()),
            # the delivery: src + emit anchor -> sidecar flow branch
            ReplayEvent(time_ns=5_000, kind=16, node=0, src=1,
                        args=(tok, 0), pay=(), emit_ns=1_000),
        ]

    def test_attempt_labeled_arrow(self):
        from madsim_tpu import obs

        doc = obs.to_perfetto(self._events(att=2))
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
        assert flows and all(
            e["name"] == "msg n1->n0 op7 try2" for e in flows
        )

    def test_attempt_zero_label_unchanged(self):
        """Off-policy (and first-attempt) tokens are plain op ids: the
        arrow name is byte-identical to the pre-retry exporter's."""
        from madsim_tpu import obs

        doc = obs.to_perfetto(self._events(att=0))
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
        assert flows and all(e["name"] == "msg n1->n0" for e in flows)

    def test_engine_kind_rows_never_decoded(self):
        """A chaos/engine row whose args alias the attempt bits must not
        grow a label — only user-kind deliveries carry op tokens."""
        from madsim_tpu.obs.perfetto import _flow_name
        from madsim_tpu.engine.replay import ReplayEvent

        e = ReplayEvent(time_ns=1, kind=2, node=0, src=1,
                        args=(retry_token(7, 2), 0), pay=())
        assert _flow_name(e) == "msg n1->n0"


# ----------------------------------------------------- soak-scale certs
@pytest.mark.slow
class TestSoakScale:
    def test_noidem_found_shrunk_replayed(self):
        """The acceptance path end-to-end: the noidem mutant is caught
        by the exactly_once hunt, ddmin-shrunk under the same policy,
        and the shrunk literal replays to the identical violation +
        trace (the LiteralPlan carries no policy, so replay passes the
        campaign's spec explicitly)."""
        wl = SK.make_shardkv(record=True, chaos=False, army=True,
                             bug="noidem")
        pol = RetryPolicy(timeout_ns=8_000_000, max_attempts=3,
                          backoff_base_ns=4_000_000, backoff_mult=2.0,
                          jitter=0.25)
        plan = FaultPlan(
            (SK.client_army(n_ops=16, t_min_ns=5_000_000,
                            t_max_ns=280_000_000, retry=pol),
             GrayFailure(targets=(0, 1), n_links=1, mult_min=8,
                         mult_max=16)),
            name="noidem-hunt",
        )
        cfg = EngineConfig(pool_size=96, time_limit_ns=600_000_000)
        rt = plan.retry_spec()

        def hinv(h):
            return check.exactly_once(h, SK.OP_ARMY_PUT)

        r = search_seeds(
            wl, cfg, None, n_seeds=32, max_steps=3000, plan=plan,
            history_invariant=hinv, latency=LatencySpec(ops=16),
            require_halt=False,
        )
        assert len(r.failing_seeds) > 0
        seed = int(r.failing_seeds[0])
        res = shrink_plan(wl, cfg, seed, plan, history_invariant=hinv,
                          max_steps=3000, latency=LatencySpec(ops=16))
        assert len(res.events) <= plan.slots
        rep = search_seeds(
            wl, cfg, None, seeds=np.asarray([seed], np.uint64),
            max_steps=3000, plan=res.plan, history_invariant=hinv,
            latency=LatencySpec(ops=16), require_halt=False, retry=rt,
        )
        assert not bool(np.asarray(rep.ok)[0])
        assert int(np.asarray(rep.traces)[0]) == int(res.trace)

    def test_retry_off_identity_soak_slice(self):
        wl = _wl()
        plan = _plan(None)
        seeds = np.arange(64, dtype=np.uint64)
        base = _run(wl, plan, seeds, None, layout="scatter", steps=3000)
        for kw in (dict(layout="dense"), dict(compact=True)):
            other = _run(wl, plan, seeds, None, steps=3000, **kw)
            for f in ("trace", "now", "halted", "met"):
                assert np.array_equal(np.asarray(getattr(base, f)),
                                      np.asarray(getattr(other, f)))
