"""madsim_tpu.check — operation-history recording + workload checkers.

Three layers under test: the host-side history model and checkers over
synthetic histories (pure numpy/python, no engine), the batched engine
integration (kvchaos/raft record modes through ``search_seeds``), and
the proof-of-value mutation test — the seeded lost-write bug that the
history checker catches while the final-state invariant provably
passes it.
"""

import dataclasses

import numpy as np
import pytest

import jax

from madsim_tpu.check import (
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    OP_READ,
    OP_USER,
    OP_WRITE,
    BatchHistory,
    HistoryError,
    Op,
    Recorder,
    check_kv,
    check_register,
    election_safety,
    monotonic_reads,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds
from madsim_tpu.engine.core import EmitBuilder, HistorySpec, make_init, make_run
from madsim_tpu.engine.verify import check_determinism, compare_traces
from madsim_tpu.models import make_kvchaos, make_raft
from madsim_tpu.models.raft import OP_ELECT
from madsim_tpu.runtime.rand import DeterminismError

W = 5  # kvchaos writes used throughout the engine-integration tests


# --------------------------------------------------------------- helpers
def _hist(*seeds):
    """Synthetic BatchHistory: each seed a list of
    (op, key, arg, client, ok, t) records in buffer order."""
    s = len(seeds)
    h = max((len(rows) for rows in seeds), default=0)
    word = np.zeros((s, h, 5), np.int32)
    t = np.zeros((s, h), np.int64)
    count = np.zeros((s,), np.int32)
    for i, rows in enumerate(seeds):
        count[i] = len(rows)
        for j, (op, key, arg, client, ok, ts) in enumerate(rows):
            word[i, j] = (op, key, arg, client, ok)
            t[i, j] = ts
    return BatchHistory(word=word, t=t, count=count,
                        drop=np.zeros((s,), np.int32))


def _op(op, arg_inv, arg_res, ok, t_inv, t_res, idx_inv, idx_res,
        client=0, key=0):
    return Op(client, op, key, arg_inv, arg_res, ok, t_inv, t_res,
              idx_inv=idx_inv, idx_res=idx_res)


def _durability(v):
    """The existing final-state invariant for kvchaos (config-5 shape,
    tools/search_soak.py): client saw all W commits and the final write
    is durable on >= R-1 of the 4 RAM-only replicas at halt."""
    ns = np.asarray(v["node_state"])
    client_done = ns[:, 5, 0] == W
    durable = (ns[:, 1:5, 0] >= W).sum(axis=1)
    return client_done & (durable >= 3)


def _capture(checker):
    """Wrap a history invariant so the BatchHistory it saw is kept."""
    box = {}

    def inv(h):
        box["h"] = h
        return checker(h)

    return inv, box


# ------------------------------------------------- linearize: register
class TestCheckRegister:
    def test_sequential_history_linearizes(self):
        ops = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 0, 1),
            _op(OP_READ, 0, 1, OK_OK, 20, 30, 2, 3),
            _op(OP_WRITE, 2, 2, OK_OK, 40, 50, 4, 5),
            _op(OP_READ, 0, 2, OK_OK, 60, 70, 6, 7),
        ]
        r = check_register(ops)
        assert r.ok and bool(r) and r.n_ops == 4

    def test_lost_write_is_rejected(self):
        # write(1) completed strictly before the read was invoked, yet
        # the read observed the initial value: no linearization exists
        ops = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 0, 1),
            _op(OP_READ, 0, 0, OK_OK, 20, 30, 2, 3),
        ]
        r = check_register(ops)
        assert not r.ok and "no linearization" in r.reason

    def test_same_timestamp_tie_breaks_by_record_index(self):
        # a write response and a read invoke recorded by the same
        # handler share a sim-time; the record index orders them, so a
        # read observing the pre-write value is still a violation
        ops = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 20, 0, 1),
            _op(OP_READ, 0, 0, OK_OK, 20, 30, 2, 3),  # t_inv == t_res(w)
        ]
        assert not check_register(ops).ok

    def test_overlapping_reads_may_resolve_out_of_order(self):
        # the pipeline artifact: read A is invoked before write 2 and is
        # still in flight while write 2 completes and read B returns 2;
        # read A then returns the OLDER value 1. The client observed
        # 2-then-1, but read A may linearize before write 2 — legal
        # (monotonic_reads would flag this response order; the exact
        # checker is the authority)
        ops = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 0, 1),
            _op(OP_READ, 0, 1, OK_OK, 20, 70, 2, 7),
            _op(OP_WRITE, 2, 2, OK_OK, 30, 40, 3, 4),
            _op(OP_READ, 0, 2, OK_OK, 50, 60, 5, 6),
        ]
        assert check_register(ops).ok

    def test_pending_write_is_optional(self):
        # a never-responded write may or may not have taken effect:
        # reads observing either value linearize
        pend = _op(OP_WRITE, 1, 0, OK_PENDING, 0, None, 0, None)
        saw_new = _op(OP_READ, 0, 1, OK_OK, 10, 20, 1, 2)
        saw_old = _op(OP_READ, 0, 0, OK_OK, 10, 20, 1, 2)
        assert check_register([pend, saw_new]).ok
        assert check_register([pend, saw_old]).ok

    def test_failed_write_is_optional_too(self):
        failed = _op(OP_WRITE, 7, 0, OK_FAIL, 0, 5, 0, 1)
        saw = _op(OP_READ, 0, 7, OK_OK, 10, 20, 2, 3)
        assert check_register([failed, saw]).ok

    def test_pending_read_constrains_nothing(self):
        ops = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 0, 1),
            _op(OP_READ, 0, 0, OK_PENDING, 20, None, 2, None),
        ]
        r = check_register(ops)
        assert r.ok and r.n_ops == 1  # the pending read was discarded

    def test_rejects_foreign_op_kinds(self):
        with pytest.raises(ValueError, match="OP_READ/OP_WRITE"):
            check_register([_op(OP_USER, 0, 0, OK_OK, 0, 1, 0, 1)])

    def test_bitmask_bound_is_enforced(self):
        ops = [
            _op(OP_WRITE, i, i, OK_OK, 10 * i, 10 * i + 5, 2 * i, 2 * i + 1)
            for i in range(64)
        ]
        with pytest.raises(ValueError, match="63-op"):
            check_register(ops)


class TestCheckKv:
    def test_keys_check_independently(self):
        ok_key = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 0, 1, key=1),
            _op(OP_READ, 0, 1, OK_OK, 20, 30, 2, 3, key=1),
        ]
        bad_key = [
            _op(OP_WRITE, 1, 1, OK_OK, 0, 10, 4, 5, key=2),
            _op(OP_READ, 0, 0, OK_OK, 20, 30, 6, 7, key=2),
        ]
        assert check_kv(ok_key).ok
        r = check_kv(ok_key + bad_key)
        assert not r.ok and "key 2" in r.reason


# ------------------------------------------------- history: pairing
class TestBatchHistoryOps:
    def test_fifo_pairing_and_instantaneous_events(self):
        h = _hist([
            (OP_WRITE, 0, 1, 5, OK_PENDING, 100),
            (OP_WRITE, 0, 1, 5, OK_OK, 200),
            (OP_USER, 3, 9, 2, OK_OK, 300),  # no invoke: instantaneous
            (OP_READ, 0, 0, 5, OK_PENDING, 400),
        ])
        ops = h.ops(0)
        assert len(ops) == 3
        w, ev, r = ops
        assert (w.ok, w.t_inv, w.t_res, w.idx_inv, w.idx_res) == \
            (OK_OK, 100, 200, 0, 1)
        assert (ev.t_inv, ev.t_res, ev.idx_inv, ev.idx_res) == \
            (300, 300, 2, 2)
        assert r.ok == OK_PENDING and r.t_res is None and r.idx_res is None

    def test_fifo_closes_oldest_invoke(self):
        h = _hist([
            (OP_READ, 0, 0, 5, OK_PENDING, 10),
            (OP_READ, 0, 0, 5, OK_PENDING, 20),
            (OP_READ, 0, 7, 5, OK_OK, 30),
        ])
        ops = h.ops(0)
        assert ops[0].arg_res == 7 and ops[0].ok == OK_OK
        assert ops[1].ok == OK_PENDING

    def test_strict_refuses_overflowed_seed(self):
        h = _hist([(OP_WRITE, 0, 1, 5, OK_OK, 10)])
        h.drop[0] = 3
        with pytest.raises(HistoryError, match="dropped 3"):
            h.ops(0)
        assert len(h.ops(0, strict=False)) == 1

    def test_valid_mask_and_columns(self):
        h = _hist(
            [(OP_WRITE, 0, 1, 5, OK_OK, 10)],
            [(OP_WRITE, 0, 1, 5, OK_OK, 10), (OP_READ, 0, 1, 5, OK_OK, 20)],
        )
        assert h.n_seeds == 2 and len(h) == 2
        assert h.valid().tolist() == [[True, False], [True, True]]
        assert not h.overflowed().any()


# ------------------------------------------------- vectorized checkers
class TestVectorized:
    def test_monotonic_reads(self):
        clean = [
            (OP_READ, 0, 1, 5, OK_OK, 10),
            (OP_READ, 0, 2, 5, OK_OK, 20),
        ]
        regress = [
            (OP_READ, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 1, 5, OK_OK, 20),
        ]
        other_key = [
            (OP_READ, 1, 2, 5, OK_OK, 10),
            (OP_READ, 2, 1, 5, OK_OK, 20),  # different key: no pair
        ]
        ok = monotonic_reads(_hist(clean, regress, other_key))
        assert ok.tolist() == [True, False, True]

    def test_monotonic_reads_tolerates_pipelined_completions(self):
        # two reads open CONCURRENTLY may legally complete out of order:
        # the interval-aware default must not flag, the strict opt-in
        # pass does (the documented unsoundness it keeps)
        from madsim_tpu.check import monotonic_reads_strict

        pipelined = [
            (OP_READ, 0, 0, 5, OK_PENDING, 0),
            (OP_READ, 0, 0, 5, OK_PENDING, 1),
            (OP_READ, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 1, 5, OK_OK, 20),
        ]
        # sequential paired reads that regress: flagged by both
        seq_regress = [
            (OP_READ, 0, 0, 5, OK_PENDING, 0),
            (OP_READ, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 0, 5, OK_PENDING, 15),
            (OP_READ, 0, 1, 5, OK_OK, 20),
        ]
        h = _hist(pipelined, seq_regress)
        assert monotonic_reads(h).tolist() == [True, False]
        assert monotonic_reads_strict(h).tolist() == [False, False]

    def test_stale_reads_lost_write(self):
        # write 2 completed before the read was invoked, read saw 1
        stale = [
            (OP_WRITE, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 0, 5, OK_PENDING, 20),
            (OP_READ, 0, 1, 5, OK_OK, 30),
        ]
        # write completed only while the read was in flight: no flag
        racing = [
            (OP_READ, 0, 0, 5, OK_PENDING, 5),
            (OP_WRITE, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 1, 5, OK_OK, 30),
        ]
        ok = stale_reads(_hist(stale, racing))
        assert ok.tolist() == [False, True]

    def test_bare_read_response_does_not_misalign_rank_matching(self):
        # an instantaneous (bare) read response recorded before any
        # invoke must not consume the FIFO rank of a later paired read
        # and inherit that invoke's write floor — linearizable history,
        # must stay clean
        bare = [
            (OP_READ, 0, 0, 5, OK_OK, 0),  # bare: no invoke pending
            (OP_WRITE, 0, 5, 5, OK_OK, 10),
            (OP_READ, 0, 0, 5, OK_PENDING, 20),
            (OP_READ, 0, 5, 5, OK_OK, 30),
        ]
        assert stale_reads(_hist(bare)).tolist() == [True]
        assert read_your_writes(_hist(bare)).tolist() == [True]

    def test_read_your_writes_scopes_to_own_client(self):
        # client 6 reads below client 5's completed write: flagged by
        # stale_reads (any-writer floor) but NOT read-your-writes
        cross = [
            (OP_WRITE, 0, 2, 5, OK_OK, 10),
            (OP_READ, 0, 0, 6, OK_PENDING, 20),
            (OP_READ, 0, 1, 6, OK_OK, 30),
        ]
        own = [
            (OP_WRITE, 0, 2, 6, OK_OK, 10),
            (OP_READ, 0, 0, 6, OK_PENDING, 20),
            (OP_READ, 0, 1, 6, OK_OK, 30),
        ]
        h = _hist(cross, own)
        assert stale_reads(h).tolist() == [False, False]
        assert read_your_writes(h).tolist() == [True, False]

    def test_election_safety(self):
        clean = [
            (OP_ELECT, 1, 3, 3, OK_OK, 10),
            (OP_ELECT, 2, 4, 4, OK_OK, 20),  # new term, new winner: fine
        ]
        split = [
            (OP_ELECT, 1, 3, 3, OK_OK, 10),
            (OP_ELECT, 1, 4, 4, OK_OK, 20),  # two winners, one term
        ]
        ok = election_safety(_hist(clean, split), elect_op=OP_ELECT)
        assert ok.tolist() == [True, False]

    def test_empty_history_is_clean(self):
        h = BatchHistory(
            word=np.zeros((3, 0, 5), np.int32), t=np.zeros((3, 0), np.int64),
            count=np.zeros((3,), np.int32), drop=np.zeros((3,), np.int32),
        )
        assert monotonic_reads(h).all()
        assert stale_reads(h).all()
        assert read_your_writes(h).all()
        assert election_safety(h, elect_op=OP_ELECT).all()


# ------------------------------------------------- Recorder (runtime)
class TestRecorder:
    def test_invoke_respond_roundtrip(self):
        clock = iter(range(0, 1000, 10))
        rec = Recorder(clock=lambda: next(clock))
        t1 = rec.invoke(client=0, op=OP_WRITE, key=1, arg=42)
        rec.respond(t1, ok=True, value=42)
        t2 = rec.invoke(client=0, op=OP_READ, key=1)
        rec.respond(t2, ok=True, value=42)
        rec.event(client=9, op=OP_USER, key=3, arg=7)
        assert len(rec) == 5
        # the KV model rejects workload-specific events: filter them
        with pytest.raises(ValueError, match="OP_READ/OP_WRITE"):
            rec.check_kv()
        ops = [o for o in rec.ops() if o.op != OP_USER]
        assert check_kv(ops).ok

    def test_out_of_order_responses_pair_by_token(self):
        # two reads concurrently open on one (client, key), responding
        # in the opposite order of their invokes; engine-style FIFO
        # pairing would hand r1's late value-0 response to r2 (invoked
        # after the write completed) and false-flag — token pairing
        # keeps the history linearizable
        clock = iter(range(0, 1000, 10))
        rec = Recorder(clock=lambda: next(clock))
        r1 = rec.invoke(client=0, op=OP_READ, key=0)
        w = rec.invoke(client=1, op=OP_WRITE, key=0, arg=1)
        rec.respond(w, ok=True, value=1)
        r2 = rec.invoke(client=0, op=OP_READ, key=0)
        rec.respond(r2, ok=True, value=1)
        rec.respond(r1, ok=True, value=0)  # linearizes before the write
        assert rec.check_kv().ok

    def test_unknown_token_rejected(self):
        rec = Recorder(clock=lambda: 0)
        tok = rec.invoke(client=0, op=OP_WRITE, key=0, arg=1)
        rec.respond(tok)
        with pytest.raises(ValueError, match="not an open invocation"):
            rec.respond(tok)

    def test_recorder_catches_lost_write(self):
        clock = iter(range(0, 1000, 10))
        rec = Recorder(clock=lambda: next(clock))
        tok = rec.invoke(client=0, op=OP_WRITE, key=0, arg=5)
        rec.respond(tok, ok=True, value=5)
        tok = rec.invoke(client=0, op=OP_READ, key=0)
        rec.respond(tok, ok=True, value=0)  # the write vanished
        r = rec.check_register()
        assert not r.ok

    def test_recorder_batch_view_matches_vectorized_contract(self):
        rec = Recorder(clock=lambda: 7)
        rec.event(client=1, op=OP_ELECT, key=1, arg=2)
        rec.event(client=3, op=OP_ELECT, key=1, arg=4)
        assert election_safety(rec.to_batch(), elect_op=OP_ELECT).tolist() \
            == [False]


# ------------------------------------------------- engine integration
@pytest.fixture(scope="module")
def kv_record_report():
    inv, box = _capture(lambda h: stale_reads(h) & read_your_writes(h))
    rep = search_seeds(
        make_kvchaos(writes=W, record=True),
        EngineConfig(pool_size=192, loss_p=0.05),
        _durability, n_seeds=128, max_steps=1500, history_invariant=inv,
    )
    return rep, box["h"]


class TestEngineRecording:
    def test_clean_model_has_no_history_violations(self, kv_record_report):
        rep, h = kv_record_report
        assert rep.failing_seeds.size == 0
        assert rep.overflowed_seeds.size == 0
        assert rep.unhalted_seeds.size == 0

    def test_history_shape_and_capacity_sizing(self, kv_record_report):
        rep, h = kv_record_report
        # exactly 4 records per write worst-case (see make_kvchaos):
        # W invokes + W responses + W read invokes + <= W read responses
        assert h.word.shape == (128, 4 * W, 5)
        assert (h.count >= 3 * W).all() and (h.count <= 4 * W).all()
        assert (h.drop == 0).all()

    def test_whole_batch_linearizable(self, kv_record_report):
        rep, h = kv_record_report
        for s in range(h.n_seeds):
            r = check_kv(h.ops(s))
            assert r.ok, f"seed index {s}: {r.reason}"

    def test_history_timestamps_are_dispatch_ordered(self, kv_record_report):
        rep, h = kv_record_report
        for s in range(h.n_seeds):
            n = int(h.count[s])
            t = h.t[s, :n]
            assert (np.diff(t) >= 0).all()

    def test_history_invariant_requires_history_spec(self):
        with pytest.raises(ValueError, match="Workload.history=None"):
            search_seeds(
                make_kvchaos(writes=W), EngineConfig(pool_size=192),
                _durability, n_seeds=8, max_steps=100,
                history_invariant=lambda h: np.ones(8, bool),
            )

    def test_some_invariant_is_required(self):
        with pytest.raises(ValueError, match="history_invariant"):
            search_seeds(
                make_kvchaos(writes=W), EngineConfig(pool_size=192),
                None, n_seeds=8, max_steps=100,
            )

    def test_bug_flag_requires_record(self):
        with pytest.raises(ValueError, match="requires record=True"):
            make_kvchaos(writes=W, bug=True)

    def test_record_bounds_writes_to_exact_checker_limit(self):
        # 32 writes -> up to 64 ops on the single key, past the 63-op
        # Wing-Gong bound: rejected at build time, not mid-sweep
        with pytest.raises(ValueError, match="at most 31 writes"):
            make_kvchaos(writes=32, record=True)
        make_kvchaos(writes=31, record=True)  # at the bound: fine

    def test_record_without_history_spec_is_rejected(self):
        eb = EmitBuilder(k=2)
        with pytest.raises(ValueError, match="HistorySpec"):
            eb.record(OP_WRITE, 0, 1)

    def test_max_records_overflow_is_rejected(self):
        eb = EmitBuilder(k=2, r=1)
        eb.record(OP_WRITE, 0, 1)
        with pytest.raises(ValueError, match="max_records"):
            eb.record(OP_WRITE, 0, 2)

    def test_history_spec_validates(self):
        with pytest.raises(ValueError, match="capacity"):
            HistorySpec(capacity=0)
        with pytest.raises(ValueError, match="max_records"):
            HistorySpec(capacity=4, max_records=0)


class TestHistoryDeterminism:
    def test_history_columns_bit_identical_across_runs(self):
        # the satellite determinism gate: two same-seed runs produce
        # bit-identical history buffers, and compare_traces covers them
        wl = make_kvchaos(writes=W, record=True)
        cfg = EngineConfig(pool_size=192, loss_p=0.05)
        seeds = np.arange(64, dtype=np.uint64)
        init = make_init(wl, cfg)
        run = jax.jit(make_run(wl, cfg, 1500))
        a = jax.block_until_ready(run(init(seeds)))
        b = jax.block_until_ready(run(init(seeds)))
        compare_traces(a, b, what="kvchaos-record x2")
        for f in ("hist_count", "hist_drop", "hist_word", "hist_t"):
            assert np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f))), f

    def test_compare_traces_detects_history_divergence(self):
        wl = make_kvchaos(writes=W, record=True)
        cfg = EngineConfig(pool_size=192, loss_p=0.05)
        seeds = np.arange(8, dtype=np.uint64)
        init = make_init(wl, cfg)
        run = jax.jit(make_run(wl, cfg, 1500))
        a = jax.block_until_ready(run(init(seeds)))
        # corrupt one history word of seed 3: the trace hash cannot see
        # it (histories are outside the hash), compare_traces must
        word = np.asarray(a.hist_word).copy()
        word[3, 0, 2] += 1
        b = dataclasses.replace(a, hist_word=word)
        with pytest.raises(DeterminismError, match="hist_word.*seed index 3"):
            compare_traces(a, b, what="tampered")
        compare_traces(a, b, what="tampered", history=False)  # opt-out

    def test_check_determinism_covers_record_mode(self):
        check_determinism(
            make_kvchaos(writes=W, record=True),
            EngineConfig(pool_size=192, loss_p=0.05),
            np.arange(16, dtype=np.uint64), 1500,
        )


class TestHistoryOverflow:
    def test_overflow_sets_flag_and_quarantines(self):
        # capacity 6 < the ~4W records a full run appends: every seed
        # overflows VISIBLY — hist_drop counts, search quarantines, and
        # the invariant sees quarantined seeds as EMPTY histories (its
        # verdict on them is discarded, so a strict per-seed checker
        # must not crash the sweep)
        inv, box = _capture(lambda h: stale_reads(h))
        rep = search_seeds(
            make_kvchaos(writes=W, record=True, hist_capacity=6),
            EngineConfig(pool_size=192, loss_p=0.05),
            _durability, n_seeds=32, max_steps=1500, history_invariant=inv,
        )
        h = box["h"]
        assert (h.count == 0).all()  # sanitized: nothing to judge
        assert (h.drop == 0).all()
        assert h.ops(0) == []  # strict ops() is safe on the sanitized view
        assert rep.overflowed_seeds.size == 32
        assert rep.failing_seeds.size == 0  # quarantined, not "violations"
        # the RAW columns keep the stored prefix and the loud drop count
        wl = make_kvchaos(writes=W, record=True, hist_capacity=6)
        cfg = EngineConfig(pool_size=192, loss_p=0.05)
        run = jax.jit(make_run(wl, cfg, 1500))
        st = jax.block_until_ready(run(make_init(wl, cfg)(
            np.arange(32, dtype=np.uint64))))
        raw = BatchHistory.from_state(st)
        assert (raw.drop > 0).all()
        assert (raw.count == 6).all()  # stored prefix, never more
        with pytest.raises(HistoryError, match="overflow"):
            raw.ops(0)
        assert len(raw.ops(0, strict=False)) <= 6


class TestLostWriteMutant:
    def test_history_checker_catches_what_final_state_misses(self):
        # THE point of the subsystem (ISSUE acceptance criterion): the
        # seeded lost-write mutant (bug=True forgets the primary's
        # commit point on replica rejoin; the protocol re-commits, so
        # halt states look healthy) passes the existing final-state
        # durability invariant on every seed, while the history checker
        # flags the seeds whose READ landed in the regression window.
        hinv, box = _capture(lambda h: stale_reads(h) & read_your_writes(h))
        cfg = EngineConfig(pool_size=192, loss_p=0.05)
        fbox = {}

        def durability_probe(view):
            # capture without folding into ok: one simulation serves
            # both sides (the tools/check_soak.py cert-3 pattern)
            fbox["ok"] = np.asarray(_durability(view), bool)
            return np.ones_like(fbox["ok"])

        rep_hist = search_seeds(
            make_kvchaos(writes=W, record=True, bug=True), cfg,
            durability_probe, n_seeds=1024, max_steps=1500,
            history_invariant=hinv,
        )
        h = box["h"]
        flagged = rep_hist.failing_seeds
        assert flagged.size > 0, "mutant must be caught by the history check"
        # the final-state invariant passes every seed — including the
        # mutant's victims the history check flagged
        assert fbox["ok"].all(), \
            "the final-state invariant must miss the lost write entirely"
        # and the exact checker agrees with the vectorized detector
        for s in flagged[:3]:
            i = int(np.searchsorted(rep_hist.seeds, s))
            r = check_kv(h.ops(i))
            assert not r.ok

    def test_unmutated_control_is_clean(self, kv_record_report):
        rep, h = kv_record_report
        assert rep.failing_seeds.size == 0


class TestRaftElectionHistory:
    def test_election_safety_over_recorded_wins(self):
        inv, box = _capture(
            lambda h: election_safety(h, elect_op=OP_ELECT))
        rep = search_seeds(
            make_raft(record=True), EngineConfig(pool_size=48, loss_p=0.02),
            invariant=lambda v: (v["node_state"][:, :, 0] == 2).any(axis=1),
            n_seeds=128, max_steps=600, history_invariant=inv,
        )
        h = box["h"]
        assert rep.failing_seeds.size == 0
        assert rep.unhalted_seeds.size == 0
        # the run halts at the first win: every seed recorded >= 1
        assert (h.count >= 1).all()
        assert (h.drop == 0).all()
        # recorded winners are real node ids, keys are real terms
        from madsim_tpu.check import COL_ARG, COL_KEY, COL_OK, COL_OP
        v = h.valid()
        assert (h.col(COL_OP)[v] == OP_ELECT).all()
        assert (h.col(COL_OK)[v] == OK_OK).all()
        assert ((h.col(COL_ARG)[v] >= 0) & (h.col(COL_ARG)[v] < 5)).all()
        assert (h.col(COL_KEY)[v] >= 1).all()
