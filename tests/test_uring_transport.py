"""io_uring transport (native/uring_transport.cpp): semantics + interop
with the epoll and asyncio endpoints — all three speak the same wire
format, completing the second alternative-transport slot (C28; reference
std/net/erpc.rs:24-30)."""

import asyncio
import shutil

import pytest

from madsim_tpu.std import native as native_mod
from madsim_tpu.std import net as std_net
from madsim_tpu.std import uring as uring_mod

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None
    or shutil.which("g++") is None
    or not uring_mod.available(),
    reason="native toolchain or io_uring unavailable",
)


def run(coro):
    return asyncio.run(coro)


def test_uring_to_uring_roundtrip():
    async def main():
        a = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        b = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        try:
            await a.send_to(("127.0.0.1", b.local_addr[1]), 5, {"x": [1, 2, 3]})
            payload, src = await b.recv_from(5, timeout=5)
            assert payload == {"x": [1, 2, 3]}
            await b.send_to(src, 6, "pong")
            payload2, _ = await a.recv_from(6, timeout=5)
            assert payload2 == "pong"
        finally:
            a.close()
            b.close()

    run(main())


def test_uring_large_payload_and_ordering():
    async def main():
        a = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        b = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        try:
            blob = bytes(range(256)) * 4096  # 1 MiB
            for i in range(5):
                await a.send_to(b.local_addr, 9, (i, blob))
            for i in range(5):
                (n, got), _ = await b.recv_from(9, timeout=10)
                assert n == i, "per-connection frame order is preserved"
                assert got == blob
        finally:
            a.close()
            b.close()

    run(main())


def test_uring_recv_timeout():
    async def main():
        a = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        try:
            with pytest.raises(asyncio.TimeoutError):
                await a.recv_from(1, timeout=0.2)
        finally:
            a.close()

    run(main())


def test_uring_interop_with_epoll_transport():
    # same wire format: an io_uring endpoint talks to the epoll endpoint
    async def main():
        u = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        e = await native_mod.NativeEndpoint.bind("127.0.0.1:0")
        try:
            await u.send_to(e.local_addr, 21, ["uring", "to", "epoll"])
            payload, src = await e.recv_from(21, timeout=5)
            assert payload == ["uring", "to", "epoll"]
            await e.send_to(src, 22, {"back": True})
            payload2, _ = await u.recv_from(22, timeout=5)
            assert payload2 == {"back": True}
        finally:
            u.close()
            e.close()

    run(main())


def test_uring_interop_with_asyncio_endpoint():
    async def main():
        u = await uring_mod.UringEndpoint.bind("127.0.0.1:0")
        py = await std_net.Endpoint.bind("127.0.0.1:0")
        try:
            await u.send_to(py.local_addr, 31, "from-uring")
            payload, src = await py.recv_from(31)
            assert payload == "from-uring"
            await py.send_to(src, 32, "from-asyncio")
            payload2, _ = await u.recv_from(32, timeout=5)
            assert payload2 == "from-asyncio"
        finally:
            u.close()
            await py.close()

    run(main())


def test_pick_endpoint_selects_uring_for_remote():
    # the feature seam: loopback -> shm; non-shm -> io_uring when the
    # kernel grants a ring (std/net/mod.rs:33-48 analog)
    from madsim_tpu.std.fastpath import pick_endpoint

    async def main():
        ep = await pick_endpoint("127.0.0.1:0", prefer_shm=False)
        try:
            assert isinstance(ep, uring_mod.UringEndpoint)
        finally:
            ep.close()
        ep2 = await pick_endpoint("127.0.0.1:0", prefer_shm=False,
                                  prefer_uring=False)
        try:
            assert isinstance(ep2, native_mod.NativeEndpoint)
        finally:
            ep2.close()

    run(main())
